"""Serving-plane tests (ISSUE 13): registry pinning, bucketed
micro-batching, full-sweep top-k (streamed + factor-sharded ring), and
replica availability.

Parity contracts under test:

- registry-served results are BIT-identical to direct model calls for
  all three estimators (same pinned weights, same programs);
- bucketed batches match at 1e-6 across jittered request sizes (ids
  exactly — per-row scoring is independent of the batch's padding);
- the serving sweep matches ``recommend_for_all_users`` exactly (ids
  AND score bits — same chunk widths, same programs);
- the ring-merged sharded sweep matches the single-device reference on
  the 8-device pseudo-mesh, including deliberate score ties (the
  lexicographic merge reproduces lax.top_k's lowest-id tie rule).
"""

from __future__ import annotations

import numpy as np
import pytest

from oap_mllib_tpu import serving
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.models.als import ALS, ALSModel
from oap_mllib_tpu.models.kmeans import KMeans
from oap_mllib_tpu.models.pca import PCA
from oap_mllib_tpu.serving import batcher, sweep
from oap_mllib_tpu.telemetry import metrics as tm
from oap_mllib_tpu.utils import progcache


@pytest.fixture(autouse=True)
def _clear_registry():
    from oap_mllib_tpu.serving import registry as reg

    reg.clear()
    yield
    reg.clear()


def _kmeans_model(rng, n=400, d=12, k=5):
    x = rng.normal(size=(n, d)).astype(np.float32)
    return KMeans(k=k, seed=3, max_iter=4).fit(x), x


def _als_model(rng, nu=60, ni=48, rank=5):
    u = rng.integers(0, nu, size=3000)
    i = rng.integers(0, ni, size=3000)
    r = rng.normal(size=3000).astype(np.float32)
    return ALS(rank=rank, max_iter=2, seed=1).fit(
        u, i, r, n_users=nu, n_items=ni
    )


class TestRegistry:
    def test_serve_is_keyed_like_progcache(self, rng):
        m, _ = _kmeans_model(rng)
        h1 = serving.serve(m)
        h2 = serving.serve(m)
        assert h1 is h2  # same model object -> same handle, no re-pin
        assert serving.unserve(m)
        assert not serving.unserve(m)

    def test_serve_rejects_unknown_surface(self):
        with pytest.raises(TypeError, match="cannot serve"):
            serving.serve(object())

    def test_served_bit_identical_all_estimators(self, rng):
        x = rng.normal(size=(300, 10)).astype(np.float32)
        km = KMeans(k=4, seed=2, max_iter=3).fit(x)
        hk = serving.serve(km)
        assert np.array_equal(hk.predict(x[:97]), km.predict(x[:97]))
        assert np.array_equal(hk.transform(x[:31]), km.transform(x[:31]))

        pca = PCA(k=3).fit(x)
        hp = serving.serve(pca)
        assert np.array_equal(hp.transform(x[:53]), pca.transform(x[:53]))

        als = _als_model(rng)
        ha = serving.serve(als)
        ids_m, s_m = als.recommend_for_users(
            np.arange(20), 6, with_scores=True
        )
        ids_h, s_h = ha.recommend_for_users(
            np.arange(20), 6, with_scores=True
        )
        assert np.array_equal(ids_m, ids_h)
        np.testing.assert_array_equal(s_m, s_h)
        assert np.array_equal(
            ha.recommend_for_all_users(5),
            als.recommend_for_all_users(5),
        )

    def test_zero_reupload_and_zero_recompile_on_repeat(self, rng):
        """Satellite: repeat scoring calls re-upload nothing (the pinned
        device buffer is the SAME object) and compile nothing (XLA
        ground truth)."""
        m, x = _kmeans_model(rng)
        m.predict(x[:100])  # warm: pin + compile
        pinned = m._dev_cache["centers"][1]
        before = progcache.xla_compile_count()
        m.predict(x[:100])
        m.predict(x[:100])
        assert progcache.xla_compile_count() - before == 0
        assert m._dev_cache["centers"][1] is pinned

    def test_transfer_guard_clean_request_path(self, rng):
        """The request path stages everything EXPLICITLY: a served
        predict under the transfer sanitizer's disallow guard raises on
        any implicit transfer — passing means zero hidden re-uploads."""
        from oap_mllib_tpu.utils import sanitizers

        m, x = _kmeans_model(rng)
        h = serving.serve(m)
        h.predict(x[:64])  # warm outside the guard
        set_config(sanitizers="transfer")
        try:
            with sanitizers.transfer_scope():
                ids = batcher.assign_kmeans(h.centers_dev, x[:64])
        finally:
            set_config(sanitizers="")
        assert ids.shape == (64,)

    def test_refit_invalidates_pin(self, rng):
        m, x = _kmeans_model(rng)
        m.predict(x[:10])
        old = m._dev_cache["centers"][1]
        m.cluster_centers_ = m.cluster_centers_.copy()  # a "refit"
        m.predict(x[:10])
        assert m._dev_cache["centers"][1] is not old

    def test_als_targets_pinned_across_chunks_and_calls(self, rng):
        """Satellite: one sweep chunks the query side but pins the
        target table once — and the pin survives across calls."""
        als = _als_model(rng)
        als.recommend_for_all_users(4)  # pins targets:item
        pinned = als._dev_cache["targets:item"][1]
        before = progcache.xla_compile_count()
        ids1 = als.recommend_for_all_users(4)
        ids2, _ = als._top_k_scores(
            als.user_factors_, als.item_factors_, 4, row_chunk=7
        )
        assert als._dev_cache["targets:item"][1] is pinned
        assert progcache.xla_compile_count() - before <= 2  # tail buckets
        ids3 = als.recommend_for_all_users(4)
        assert np.array_equal(ids1, ids3)

    def test_predict_many_coalesces(self, rng):
        m, x = _kmeans_model(rng)
        h = serving.serve(m)
        parts = h.predict_many([x[:7], x[7:20], x[20:21]])
        direct = m.predict(x[:21])
        assert np.array_equal(np.concatenate(parts), direct)
        assert h.requests == 3
        # the coalesced flush left the queue-depth gauge back at zero
        assert tm.gauge("oap_serve_queue_depth").value == 0

    def test_warmup_then_jittered_storm_compiles_nothing(self, rng):
        m, x = _kmeans_model(rng, n=700)
        h = serving.serve(m)
        h.warmup(512)
        before = progcache.xla_compile_count()
        for s in rng.integers(1, 512, size=50):
            h.predict(x[: int(s)])
        assert progcache.xla_compile_count() - before == 0

    def test_serving_summary_block(self, rng):
        m, x = _kmeans_model(rng)
        h = serving.serve(m)
        h.predict(x[:30])
        block = serving.serving_summary()
        assert block["models_pinned"] == 1
        assert block["requests"] >= 1
        assert block["latency_p50_s"] > 0
        assert block["latency_p99_s"] >= block["latency_p50_s"]


class TestBatcher:
    def test_bucket_batch_pads_to_geometric_bucket(self):
        x = np.ones((9, 3), np.float32)
        padded, n = batcher.bucket_batch(x)
        assert n == 9
        assert padded.shape == (16, 3)  # 8 -> 16 geometric series
        assert (padded[9:] == 0).all()

    def test_bucket_batch_off_restores_exact_padding(self):
        set_config(shape_bucketing="off")
        padded, n = batcher.bucket_batch(np.ones((9, 3), np.float32))
        assert padded.shape == (16, 3)  # multiple-of-8 exact padding

    def test_bucketed_parity_across_jittered_sizes(self, rng):
        """Bucketed scoring matches the unpadded result at 1e-6 for
        every size in a jittered storm (ids exactly; PCA projections
        to 1e-6)."""
        m, x = _kmeans_model(rng, n=600)
        pca = PCA(k=3).fit(x)
        from oap_mllib_tpu.fallback.kmeans_np import predict_np

        comp = pca.components_
        for s in rng.integers(1, 600, size=12):
            s = int(s)
            ids = m.predict(x[:s])
            assert np.array_equal(
                ids, predict_np(x[:s].astype(np.float64),
                                m.cluster_centers_.astype(np.float64),
                                "euclidean")
            ), f"ids diverge at size {s}"
            proj = pca.transform(x[:s])
            np.testing.assert_allclose(
                proj, x[:s] @ comp, atol=1e-5, rtol=1e-5
            )

    def test_warm_sizes_cover_the_range(self):
        sizes = batcher.warm_sizes(1000)
        assert sizes[-1] >= 1000
        assert sizes == sorted(set(sizes))

    def test_serving_precision_typo_raises(self, rng):
        m, x = _kmeans_model(rng)
        set_config(serving_precision="fp8")
        with pytest.raises(ValueError, match="serving_precision"):
            m.predict(x[:4])

    def test_serving_precision_override_resolves(self):
        set_config(serving_precision="tf32")
        pol = batcher.resolve_policy("kmeans")
        assert pol.name == "tf32"
        set_config(serving_precision="")
        assert batcher.resolve_policy("kmeans").name == "f32"

    def test_serve_request_fault_site_drillable(self, rng):
        from oap_mllib_tpu.utils import faults

        m, x = _kmeans_model(rng)
        m.predict(x[:8])  # warm
        set_config(fault_spec="serve.request:fail=1")
        try:
            with pytest.raises(faults.FaultInjected):
                m.predict(x[:8])
            # the armed count is consumed: the next request answers
            assert m.predict(x[:8]).shape == (8,)
        finally:
            set_config(fault_spec="")
            faults.reset()


class TestChunkSourceScoring:
    def test_kmeans_chunksource_bit_identical_to_ndarray(self, rng):
        """Satellite: disk/stream-backed scoring routes through the SAME
        bucketed serving program — bit-identical labels."""
        from oap_mllib_tpu.data.stream import ChunkSource

        m, x = _kmeans_model(rng, n=500)
        direct = m.predict(x)
        src = ChunkSource.from_array(x, chunk_rows=96)
        assert np.array_equal(m.predict(src), direct)
        # two passes over the source add no compiled shapes
        before = progcache.xla_compile_count()
        assert np.array_equal(m.predict(src), direct)
        assert progcache.xla_compile_count() - before == 0

    def test_kmeans_disk_backed_scoring(self, rng, tmp_path):
        from oap_mllib_tpu.data import io as dio
        from oap_mllib_tpu.data.stream import ChunkSource

        m, x = _kmeans_model(rng, n=300)
        path = str(tmp_path / "table.npy")
        dio.atomic_save_npy(path, x)
        src = ChunkSource.from_npy(path, chunk_rows=64)
        assert np.array_equal(m.predict(src), m.predict(x))

    def test_pca_chunksource_matches_ndarray(self, rng):
        from oap_mllib_tpu.data.stream import ChunkSource

        x = rng.normal(size=(400, 9)).astype(np.float32)
        pca = PCA(k=4).fit(x)
        src = ChunkSource.from_array(x, chunk_rows=128)
        np.testing.assert_allclose(
            pca.transform(src), pca.transform(x), atol=1e-6
        )


def _host_als(rng, nu, ni, r=5):
    """A HOST-factor ALSModel (the streamed sweep path — fitted models
    on the suite's 8-device mesh come out block-sharded and take the
    ring path instead, covered by TestShardedSweep)."""
    return ALSModel(
        rng.normal(size=(nu, r)).astype(np.float32),
        rng.normal(size=(ni, r)).astype(np.float32),
    )


class TestSweep:
    def test_sweep_matches_model_exactly(self, rng):
        als = _host_als(rng, nu=150, ni=64)
        ids_m, s_m = als.recommend_for_all_users(9, with_scores=True)
        ids_s, s_s = sweep.recommend_for_all_users(
            als, 9, with_scores=True
        )
        assert np.array_equal(ids_m, ids_s)
        np.testing.assert_array_equal(s_m, s_s)  # bit parity

    def test_sweep_of_fitted_model_matches_model(self, rng):
        als = _als_model(rng, nu=100, ni=48)
        assert np.array_equal(
            sweep.recommend_for_all_users(als, 6),
            als.recommend_for_all_users(6),
        )

    def test_sweep_chunk_override_and_tail_bucket(self, rng):
        als = _host_als(rng, nu=101, ni=32)
        ref = als.recommend_for_all_users(5)
        ids = sweep.recommend_for_all_users(als, 5, chunk_rows=17)
        assert np.array_equal(ids, ref)

    def test_sweep_clamps_num_items(self, rng):
        als = _host_als(rng, nu=20, ni=8)
        ids = sweep.recommend_for_all_users(als, 99)
        assert ids.shape == (20, 8)

    def test_sweep_zero_k_and_negative(self, rng):
        als = _host_als(rng, nu=12, ni=8)
        assert sweep.recommend_for_all_users(als, 0).shape == (12, 0)
        with pytest.raises(ValueError, match=">= 0"):
            sweep.recommend_for_all_users(als, -1)

    def test_sweep_chunk_rows_config_negative_raises(self, rng):
        als = _host_als(rng, nu=12, ni=8)
        set_config(sweep_chunk_rows=-1)
        with pytest.raises(ValueError, match="sweep_chunk_rows"):
            sweep.recommend_for_all_users(als, 2)

    def test_sweep_streamed_is_chunk_invariant(self, rng):
        """Different chunk widths produce the same answer — the fold
        never depends on how the user table was sliced."""
        als = _host_als(rng, nu=90, ni=40)
        ref = sweep.recommend_for_all_users(als, 6, chunk_rows=90)
        for rows in (7, 13, 64):
            assert np.array_equal(
                sweep.recommend_for_all_users(als, 6, chunk_rows=rows),
                ref,
            )

    def test_sweep_large_table_bounded_memory(self, rng):
        """A 200k-user synthetic factor table sweeps with O(chunk)
        device footprint (the quadratic score matrix would be 200k x
        256 = 200 MB; chunks bound it to chunk x 256).  Spot-check
        parity on sampled rows against a direct top-k."""
        nu, ni, r, k = 200_000, 256, 8, 4
        uf = rng.normal(size=(nu, r)).astype(np.float32)
        itf = rng.normal(size=(ni, r)).astype(np.float32)
        m = ALSModel(uf, itf)
        ids = sweep.recommend_for_all_users(m, k, chunk_rows=8192)
        assert ids.shape == (nu, k)
        sample = rng.integers(0, nu, size=64)
        scores = uf[sample] @ itf.T
        expect = np.argsort(-scores, axis=1, kind="stable")[:, :k]
        assert np.array_equal(ids[sample], expect)


class TestShardedSweep:
    """Factor-sharded ring sweep on the 8-device pseudo-mesh: the live
    block layout serves without a host gather, and the ring-merged
    top-k matches the single-device reference exactly."""

    def _sharded_als(self, rng, layout, nu=200, ni=96):
        set_config(als_item_layout=layout)
        u = rng.integers(0, nu, size=6000)
        i = rng.integers(0, ni, size=6000)
        r = rng.normal(size=6000).astype(np.float32)
        return ALS(rank=6, max_iter=2, seed=2).fit(
            u, i, r, n_users=nu, n_items=ni
        )

    def test_ring_sweep_matches_reference(self, rng):
        m = self._sharded_als(rng, "sharded")
        assert m._sharded_user is not None and m._sharded_item is not None
        ids, scores = sweep.recommend_for_all_users(
            m, 7, with_scores=True
        )
        ref = ALSModel(
            np.array(m.user_factors_), np.array(m.item_factors_)
        )
        ids_ref, s_ref = ref._top_k_scores(
            ref.user_factors_, ref.item_factors_, 7
        )
        assert np.array_equal(ids, ids_ref)
        np.testing.assert_array_equal(scores, s_ref)

    def test_replicated_item_sharded_user_sweep(self, rng):
        m = self._sharded_als(rng, "replicated")
        assert m._sharded_user is not None and m._sharded_item is None
        ids, scores = sweep.recommend_for_all_users(
            m, 5, with_scores=True
        )
        ref = ALSModel(
            np.array(m.user_factors_), np.array(m.item_factors_)
        )
        ids_ref, s_ref = ref._top_k_scores(
            ref.user_factors_, ref.item_factors_, 5
        )
        assert np.array_equal(ids, ids_ref)
        np.testing.assert_array_equal(scores, s_ref)

    def test_ring_merge_tie_breaking_matches_top_k(self, rng):
        """Deliberate cross-block score ties: duplicate item rows land
        in different ring blocks; the lexicographic merge must pick the
        LOWEST global id — exactly lax.top_k's tie rule on the
        unsharded reference."""
        from oap_mllib_tpu.parallel.mesh import get_mesh

        set_config(als_item_layout="sharded")
        mesh = get_mesh()
        nu, ni, r = 64, 80, 4
        uf = rng.normal(size=(nu, r)).astype(np.float32)
        base = rng.normal(size=(10, r)).astype(np.float32)
        itf = np.tile(base, (8, 1))  # every row duplicated across blocks
        ub, uoff, upp = sweep.shard_factors(uf, mesh)
        ib, ioff, ipp = sweep.shard_factors(itf, mesh)
        m = ALSModel(
            None, None,
            sharded_user=(ub, uoff, upp), sharded_item=(ib, ioff, ipp),
        )
        ids, scores = sweep.recommend_for_all_users(
            m, 12, with_scores=True
        )
        ref = ALSModel(uf, itf)
        ids_ref, s_ref = ref._top_k_scores(uf, itf, 12)
        assert np.array_equal(ids, ids_ref)
        np.testing.assert_array_equal(scores, s_ref)

    def test_shard_factors_roundtrip(self, rng):
        from oap_mllib_tpu.parallel.mesh import get_mesh

        f = rng.normal(size=(123, 6)).astype(np.float32)
        blocks, offsets, per = sweep.shard_factors(f, get_mesh())
        m = ALSModel(
            None, np.zeros((4, 6), np.float32),
            sharded_user=(blocks, offsets, per),
        )
        assert np.array_equal(m.user_factors_, f)


class TestEvictionReform:
    """ISSUE 18: a sharded sweep that loses a replica mid-flight either
    re-forms on the survivors' local layout (reform hook) or fails
    loudly naming the culprit crash records — never a silent hang."""

    def _host_tables(self, rng):
        uf = rng.normal(size=(40, 5)).astype(np.float32)
        itf = rng.normal(size=(32, 5)).astype(np.float32)
        return uf, itf

    def _local_model(self, uf, itf):
        return ALSModel(
            None, None,
            sharded_user=sweep.shard_factors_local(uf),
            sharded_item=sweep.shard_factors_local(itf),
        )

    def test_shard_factors_local_serves_bit_identical(self, rng):
        uf, itf = self._host_tables(rng)
        ids, scores = sweep.recommend_for_all_users(
            self._local_model(uf, itf), 6, with_scores=True
        )
        ref = ALSModel(uf, itf)
        ids_ref, s_ref = ref._top_k_scores(uf, itf, 6)
        assert np.array_equal(ids, ids_ref)
        np.testing.assert_array_equal(scores, s_ref)

    def test_reform_hook_reforms_once_and_answers(self, rng, monkeypatch):
        from oap_mllib_tpu.utils import recovery

        uf, itf = self._host_tables(rng)
        real = sweep._sweep_sharded
        calls = {"n": 0}

        def dies_once(model, n, ws):
            calls["n"] += 1
            if calls["n"] == 1:
                raise recovery.CollectiveTimeoutError(
                    "peer died mid-sweep"
                )
            return real(model, n, ws)

        monkeypatch.setattr(sweep, "_sweep_sharded", dies_once)
        reforms0 = tm.family_total("oap_serve_sweep_reforms_total")
        reformed = []

        def reform(exc):
            reformed.append(exc)
            return self._local_model(uf, itf)

        ids, scores = sweep.recommend_for_all_users(
            self._local_model(uf, itf), 6, with_scores=True,
            reform=reform,
        )
        ref = ALSModel(uf, itf)
        ids_ref, s_ref = ref._top_k_scores(uf, itf, 6)
        assert np.array_equal(ids, ids_ref)
        np.testing.assert_array_equal(scores, s_ref)
        assert len(reformed) == 1
        assert isinstance(
            reformed[0], recovery.CollectiveTimeoutError
        )
        assert (
            tm.family_total("oap_serve_sweep_reforms_total")
            == reforms0 + 1
        )

    def test_reform_runs_once_then_raw_recovery_error(
        self, rng, monkeypatch
    ):
        # the re-formed sweep gets NO second reform: a hook that hands
        # back another doomed mesh surfaces the recovery error raw
        from oap_mllib_tpu.utils import recovery

        uf, itf = self._host_tables(rng)

        def always_dies(model, n, ws):
            raise recovery.CollectiveTimeoutError("still doomed")

        monkeypatch.setattr(sweep, "_sweep_sharded", always_dies)
        with pytest.raises(serving.ServeError) as ei:
            sweep.recommend_for_all_users(
                self._local_model(uf, itf), 6,
                reform=lambda exc: self._local_model(uf, itf),
            )
        assert ei.value.reason == "eviction"

    def test_no_reform_hook_names_the_crash_records(
        self, rng, monkeypatch, tmp_path
    ):
        from oap_mllib_tpu.utils import recovery

        set_config(crash_dir=str(tmp_path))
        recovery.write_crash_record(
            "serve.heartbeat", "collective_timeout", "peer preempted"
        )

        def dead_mesh(model, n, ws):
            raise recovery.PeerAbortError("mesh spans a dead peer")

        monkeypatch.setattr(sweep, "_sweep_sharded", dead_mesh)
        uf, itf = self._host_tables(rng)
        with pytest.raises(serving.ServeError) as ei:
            sweep.recommend_for_all_users(self._local_model(uf, itf), 6)
        err = ei.value
        assert err.reason == "eviction"
        assert isinstance(err.__cause__, recovery.PeerAbortError)
        assert len(err.crash_records) == 1
        assert "crash" in str(err)  # the culprit record is NAMED

    def test_list_crash_records_filters_and_sorts(self, tmp_path):
        from oap_mllib_tpu.utils import recovery

        set_config(crash_dir=str(tmp_path))
        recovery.write_crash_record("site.a", "unclassified", "x")
        (tmp_path / "serve.drain.done.rank0.json").write_text("{}")
        (tmp_path / "notes.txt").write_text("ignore")
        recs = recovery.list_crash_records(str(tmp_path))
        assert len(recs) == 1
        assert recs[0].endswith(".json") and "crash" in recs[0]
        assert recovery.list_crash_records(
            str(tmp_path / "missing")
        ) == []


class TestHA:
    def test_heartbeat_single_process_view(self):
        view = serving.heartbeat(requests=7, queue_depth=2)
        assert view["world"] == 1
        assert view["requests"] == [7]
        assert view["queue_depth"] == [2]

    def test_replica_guard_absorbs_recovery_errors(self):
        from oap_mllib_tpu.utils import recovery

        guard = serving.ReplicaGuard()
        before = tm.family_total("oap_serve_evictions_total")
        with guard.leg():
            raise recovery.CollectiveTimeoutError(
                "peer missed deadline", op="process_allgather",
                axis="host", elapsed_s=10.0,
            )
        assert guard.local_only
        assert guard.evictions == 1
        assert isinstance(
            guard.last_error, recovery.CollectiveTimeoutError
        )
        assert tm.family_total("oap_serve_evictions_total") == before + 1

    def test_replica_guard_propagates_other_errors(self):
        guard = serving.ReplicaGuard()
        with pytest.raises(ValueError):
            with guard.leg():
                raise ValueError("a genuine bug")
        assert not guard.local_only


class TestMetricsQuantile:
    def test_histogram_quantile_bucket_upper_bounds(self):
        h = tm.Histogram(bounds=(1.0, 4.0, 16.0))
        for v in (0.5, 0.5, 3.0, 10.0):
            h.observe(v)
        assert tm.histogram_quantile(h, 0.5) == 1.0
        assert tm.histogram_quantile(h, 0.99) == 16.0
        with pytest.raises(ValueError):
            tm.histogram_quantile(h, -0.1)
        with pytest.raises(ValueError):
            tm.histogram_quantile(h, 1.01)

    def test_quantile_empty_histogram(self):
        h = tm.Histogram(bounds=(1.0, 2.0))
        for q in (0.0, 0.5, 1.0):
            assert tm.histogram_quantile(h, q) == 0.0

    def test_quantile_q0_is_min_estimate(self):
        # q=0 names the lowest NON-EMPTY bucket, not bounds[0]
        h = tm.Histogram(bounds=(1.0, 4.0, 16.0))
        h.observe(3.0)
        h.observe(10.0)
        assert tm.histogram_quantile(h, 0.0) == 4.0

    def test_quantile_single_bucket_mass(self):
        # all mass in one bucket: every quantile names that bucket
        h = tm.Histogram(bounds=(1.0, 4.0, 16.0))
        for _ in range(7):
            h.observe(2.0)
        for q in (0.0, 0.25, 0.5, 1.0):
            assert tm.histogram_quantile(h, q) == 4.0

    def test_quantile_overflow_clamps_to_last_finite_bound(self):
        # mass past the largest finite bound has no upper witness:
        # q=0, q=1, and everything between clamp to bounds[-1]
        h = tm.Histogram(bounds=(1.0, 4.0))
        h.observe(100.0)
        for q in (0.0, 0.5, 1.0):
            assert tm.histogram_quantile(h, q) == 4.0

    def test_quantile_q1_is_max_bucket(self):
        h = tm.Histogram(bounds=(1.0, 4.0, 16.0))
        h.observe(0.5)
        h.observe(12.0)
        assert tm.histogram_quantile(h, 1.0) == 16.0
