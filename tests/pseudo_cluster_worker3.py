"""Worker for the 3-process pseudo-cluster variant.

The reference only ever tested 2 executors (its pseudo-YARN cluster,
dev/test-cluster/env.sh); this stresses a world size that is neither a
power of two nor the tested-everywhere 2: UNEVEN thirds through the
in-memory mesh path AND the streamed per-process-source path.

Invoked as:  python pseudo_cluster_worker3.py RANK NPROC COORD LOCAL_DEVICES
"""

import json
import sys

rank, nproc = int(sys.argv[1]), int(sys.argv[2])
coord, local_dev = sys.argv[3], int(sys.argv[4])

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", local_dev)

import numpy as np

from oap_mllib_tpu.parallel import bootstrap

assert bootstrap.initialize_distributed(coord, nproc, rank)
assert jax.process_count() == nproc

from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.models.kmeans import KMeans
from oap_mllib_tpu.models.pca import PCA

# same global dataset as the 2-process worker; uneven thirds
rng = np.random.default_rng(123)
proto = rng.normal(size=(5, 12)).astype(np.float32) * 3.0
x = (proto[rng.integers(5, size=4000)]
     + rng.normal(size=(4000, 12)).astype(np.float32) * 0.25)
cuts = [0, 1300, 2600, 4000]
shard = x[cuts[rank] : cuts[rank + 1]]

m = KMeans(k=5, seed=7, max_iter=30).fit(shard)
assert m.summary.accelerated

p = PCA(k=4).fit(shard)

ms = KMeans(k=5, seed=7, max_iter=30).fit(
    ChunkSource.from_array(shard, chunk_rows=300)
)
assert getattr(ms.summary, "streamed", False)
ps = PCA(k=4).fit(ChunkSource.from_array(shard, chunk_rows=300))
assert ps.summary["n_rows"] == 4000

print(
    "RESULT "
    + json.dumps(
        {
            "rank": rank,
            "kmeans_cost": float(m.summary.training_cost),
            "pca_var": np.asarray(p.explained_variance_).tolist(),
            "streamed_cost": float(ms.summary.training_cost),
            "streamed_pca_var": np.asarray(ps.explained_variance_).tolist(),
        }
    ),
    flush=True,
)
