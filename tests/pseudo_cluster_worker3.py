"""Worker for the 3-process pseudo-cluster variant.

The reference only ever tested 2 executors (its pseudo-YARN cluster,
dev/test-cluster/env.sh); this stresses a world size that is neither a
power of two nor the tested-everywhere 2: UNEVEN thirds through the
in-memory mesh path AND the streamed per-process-source path.

Invoked as:  python pseudo_cluster_worker3.py RANK NPROC COORD LOCAL_DEVICES
"""

import json
import sys

rank, nproc = int(sys.argv[1]), int(sys.argv[2])
coord, local_dev = sys.argv[3], int(sys.argv[4])

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    # older jax lines have no jax_num_cpu_devices config option; the env
    # flag must be in place before the backend initializes
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={local_dev}"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
if hasattr(jax.config, "jax_num_cpu_devices"):
    jax.config.update("jax_num_cpu_devices", local_dev)

import numpy as np

from oap_mllib_tpu.parallel import bootstrap

assert bootstrap.initialize_distributed(coord, nproc, rank)
assert jax.process_count() == nproc

from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.models.kmeans import KMeans
from oap_mllib_tpu.models.pca import PCA

# same global dataset as the 2-process worker; uneven thirds
rng = np.random.default_rng(123)
proto = rng.normal(size=(5, 12)).astype(np.float32) * 3.0
x = (proto[rng.integers(5, size=4000)]
     + rng.normal(size=(4000, 12)).astype(np.float32) * 0.25)
cuts = [0, 1300, 2600, 4000]
shard = x[cuts[rank] : cuts[rank + 1]]

m = KMeans(k=5, seed=7, max_iter=30).fit(shard)
assert m.summary.accelerated

p = PCA(k=4).fit(shard)

ms = KMeans(k=5, seed=7, max_iter=30).fit(
    ChunkSource.from_array(shard, chunk_rows=300)
)
assert getattr(ms.summary, "streamed", False)
ps = PCA(k=4).fit(ChunkSource.from_array(shard, chunk_rows=300))
assert ps.summary["n_rows"] == 4000

# item-sharded ALS over a 3-rank world: a block count that is neither a
# power of two nor 2 exercises the item-block offsets/padding (last
# block short) through the second shuffle + all_gather exchange
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.models.als import ALS

rng_als = np.random.default_rng(77)
NU, NI, RANK_ = 60, 40, 3
au = rng_als.integers(NU, size=1200).astype(np.int64)
ai = rng_als.integers(NI, size=1200).astype(np.int64)
au[0], ai[0] = NU - 1, NI - 1
ar = rng_als.random(1200).astype(np.float32) * 4 + 1
acuts = [0, 400, 800, 1200]
asl = slice(acuts[rank], acuts[rank + 1])
set_config(als_item_layout="sharded")
m_sh = ALS(rank=RANK_, max_iter=3, reg_param=0.1, implicit_prefs=True,
           seed=3).fit(au[asl], ai[asl], ar[asl])
assert m_sh.summary["item_layout"] == "sharded"

# streamed-block 2-D composition over the SAME 3-rank world: each rank
# streams its local triples; the single-sweep double redistribution and
# the short last item block (kpb_i=14, 40 items over 3 blocks) cross
# the process boundary (ops/als_block_stream)
set_config(als_kernel="grouped")
trip3 = np.stack(
    [au[asl].astype(np.float64), ai[asl].astype(np.float64),
     ar[asl].astype(np.float64)], axis=1,
)
m_st3 = ALS(rank=RANK_, max_iter=3, reg_param=0.1, implicit_prefs=True,
            seed=3).fit(ChunkSource.from_array(trip3, chunk_rows=200))
assert m_st3.summary.get("streamed"), m_st3.summary
assert m_st3.summary["item_layout"] == "sharded", m_st3.summary
set_config(als_item_layout="auto", als_kernel="auto")

print(
    "RESULT "
    + json.dumps(
        {
            "rank": rank,
            "kmeans_cost": float(m.summary.training_cost),
            "pca_var": np.asarray(p.explained_variance_).tolist(),
            "streamed_cost": float(ms.summary.training_cost),
            "streamed_pca_var": np.asarray(ps.explained_variance_).tolist(),
            "als_sh_if": np.asarray(m_sh.item_factors_).tolist(),
            "als_st3_if": np.asarray(m_st3.item_factors_).tolist(),
        }
    ),
    flush=True,
)
