"""Memory-budget planner tests (utils/membudget.py — ISSUE 12).

Covers the tentpole contracts: the budget grammar and detection
fallbacks, the per-algorithm decision table (footprint x budget ->
route), summary.route exposure with every candidate's estimate and
rejection reason, strict-mode BudgetError, pin: overrides, the
estimate-vs-actual bytes-staged cross-check on real fits, and the
oap_route_* metric surface.
"""

import numpy as np
import pytest

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.telemetry import metrics as tm
from oap_mllib_tpu.utils import membudget as mb


@pytest.fixture(autouse=True)
def _clean_budgets():
    set_config(
        memory_budget_hbm="unlimited", memory_budget_host="unlimited",
        scale_policy="auto",
    )
    mb.reset_calibration()
    yield
    set_config(
        memory_budget_hbm="", memory_budget_host="", scale_policy="auto"
    )
    mb.reset_calibration()


def _blobs(rng, n=600, d=6):
    proto = rng.normal(size=(3, d)).astype(np.float32) * 4.0
    return (proto[rng.integers(3, size=n)]
            + rng.normal(size=(n, d)).astype(np.float32) * 0.2)


class TestBudgetGrammar:
    def test_parse_sizes(self):
        assert mb.parse_budget("") is None  # auto-detect
        assert mb.parse_budget("0") == 0  # unbounded
        assert mb.parse_budget("unlimited") == 0
        assert mb.parse_budget("1024") == 1024
        assert mb.parse_budget("4K") == 4096
        assert mb.parse_budget("512m") == 512 << 20
        assert mb.parse_budget("2G") == 2 << 30
        assert mb.parse_budget("1.5g") == int(1.5 * (1 << 30))

    def test_typo_raises(self):
        with pytest.raises(ValueError, match="K/M/G/T"):
            mb.parse_budget("12Q")
        with pytest.raises(ValueError, match=">= 0"):
            mb.parse_budget("-5M")

    def test_detection_fallbacks_never_raise(self):
        assert mb.detect_hbm_bytes() >= 0
        assert mb.detect_host_bytes() >= 0

    def test_budgets_resolve_sources(self):
        set_config(memory_budget_hbm="64M", memory_budget_host="")
        b = mb.Budgets.resolve()
        assert b.hbm == 64 << 20 and b.hbm_source == "config"
        assert b.host_source == "detected"

    def test_scale_policy_grammar(self):
        set_config(scale_policy="strict")
        assert mb.scale_policy_cfg() == ("strict", None)
        set_config(scale_policy="pin:streamed")
        assert mb.scale_policy_cfg() == ("pin", "streamed")
        set_config(scale_policy="pin:bogus")
        with pytest.raises(ValueError, match="pin route"):
            mb.scale_policy_cfg()
        set_config(scale_policy="sometimes")
        with pytest.raises(ValueError, match="scale_policy"):
            mb.scale_policy_cfg()


# footprint x budget -> route: the planner's decision table, pinned.
# Budgets are synthetic so the decisions are deterministic everywhere.
KMEANS_TABLE = [
    # (n, d, k, hbm_budget, expected_route)
    (1_000, 8, 3, "unlimited", mb.ROUTE_IN_MEMORY),
    (1_000_000, 256, 1000, "unlimited", mb.ROUTE_CHUNKED),
    (200_000, 64, 8, "120M", mb.ROUTE_STREAMED),  # table > budget
    (1_000, 8, 3, "1G", mb.ROUTE_IN_MEMORY),
]


class TestDecisionTable:
    @pytest.mark.parametrize("n,d,k,budget,route", KMEANS_TABLE)
    def test_kmeans_routes(self, n, d, k, budget, route):
        set_config(memory_budget_hbm=budget)
        from oap_mllib_tpu.ops.kmeans_ops import auto_row_chunks

        plan = mb.plan_kmeans(
            n, d, k, row_chunks_hint=auto_row_chunks(n, k)
        )
        assert plan.route == route, plan.as_dict()

    def test_pca_routes(self):
        plan = mb.plan_pca(2_000, 16)
        assert plan.route == mb.ROUTE_IN_MEMORY
        set_config(memory_budget_hbm="100M")
        plan = mb.plan_pca(2_000_000, 128)
        assert plan.route == mb.ROUTE_STREAMED
        rejected = plan.estimate_for(mb.ROUTE_IN_MEMORY)
        assert "hbm estimate" in rejected.reject

    def test_als_routes(self):
        plan = mb.plan_als(10_000, 500, 300, 8)
        assert plan.route == mb.ROUTE_IN_MEMORY
        # grouped layouts past the budget -> streamed kernels
        set_config(memory_budget_hbm="90M")
        plan = mb.plan_als(50_000_000, 100_000, 50_000, 16)
        assert plan.route == mb.ROUTE_STREAMED
        # a mesh world plans the block route
        plan = mb.plan_als(10_000, 500, 300, 8, world=4)
        assert plan.route == mb.ROUTE_STREAMED_BLOCK

    def test_source_inputs_stream_naturally(self):
        plan = mb.plan_kmeans(
            1_000, 8, 3, source_backing="memory", chunk_rows=128
        )
        assert plan.route == mb.ROUTE_STREAMED
        assert plan.natural == mb.ROUTE_STREAMED
        assert not plan.degraded_scale

    def test_over_budget_is_recorded_not_silent(self):
        set_config(memory_budget_hbm="1M")
        plan = mb.plan_kmeans(1_000_000, 256, 100)
        assert plan.route == mb.ROUTE_STREAMED  # most scale-capable
        assert plan.over_budget
        assert all(e.reject for e in plan.estimates)

    def test_budget_narrows_streamed_chunks(self):
        set_config(memory_budget_hbm="32M")
        plan = mb.plan_kmeans(10_000_000, 256, 100)
        from oap_mllib_tpu.data.stream import DEFAULT_CHUNK_ROWS

        assert plan.chunk_rows < DEFAULT_CHUNK_ROWS
        from oap_mllib_tpu.utils.resilience import OOM_CHUNK_FLOOR_ROWS

        assert plan.chunk_rows >= OOM_CHUNK_FLOOR_ROWS


class TestPolicy:
    def test_strict_raises_instead_of_degrading(self):
        set_config(memory_budget_hbm="120M", scale_policy="strict")
        with pytest.raises(mb.BudgetError, match="strict"):
            mb.plan_kmeans(200_000, 64, 8)

    def test_strict_passes_when_natural_fits(self):
        set_config(scale_policy="strict")
        plan = mb.plan_kmeans(1_000, 8, 3)
        assert plan.route == mb.ROUTE_IN_MEMORY

    def test_budget_error_names_candidates(self):
        set_config(memory_budget_hbm="120M", scale_policy="strict")
        with pytest.raises(mb.BudgetError, match="in-memory.*hbm"):
            mb.plan_kmeans(200_000, 64, 8)

    def test_pin_overrides_budget(self):
        set_config(memory_budget_hbm="1", scale_policy="pin:in-memory")
        plan = mb.plan_kmeans(10_000, 16, 4)
        assert plan.route == mb.ROUTE_IN_MEMORY and plan.forced

    def test_pin_streams_small_fits(self):
        set_config(scale_policy="pin:streamed")
        plan = mb.plan_kmeans(100, 4, 2)
        assert plan.route == mb.ROUTE_STREAMED

    def test_pin_inapplicable_route_raises(self):
        set_config(scale_policy="pin:streamed-block")
        with pytest.raises(ValueError, match="does not apply"):
            mb.plan_kmeans(100, 4, 2)

    def test_downgrade_strict_vs_auto(self):
        plan = mb.plan_kmeans(
            1_000, 8, 3, source_backing="memory", chunk_rows=128
        )
        set_config(scale_policy="strict")
        with pytest.raises(mb.BudgetError, match="downgrading"):
            plan.downgrade(mb.ROUTE_IN_MEMORY, "test downgrade")
        set_config(scale_policy="auto")
        plan.downgrade(mb.ROUTE_IN_MEMORY, "test downgrade")
        assert plan.route == mb.ROUTE_IN_MEMORY
        assert plan.downgrades and "test downgrade" in plan.downgrades[0]


class TestFitIntegration:
    """summary.route on real fits: decision + inputs, strict raising at
    fit entry, pin overrides actually changing the executed route."""

    def test_kmeans_summary_route(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        m = KMeans(k=3, seed=1, max_iter=2).fit(_blobs(rng))
        r = m.summary.route
        assert r["route"] == mb.ROUTE_IN_MEMORY
        assert r["policy"] == "auto"
        assert {e["route"] for e in r["estimates"]} == {
            mb.ROUTE_IN_MEMORY, mb.ROUTE_CHUNKED, mb.ROUTE_STREAMED
        }
        assert r["budgets"]["hbm_source"] == "config"

    def test_budget_forces_array_fit_onto_streamed(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _blobs(rng)
        baseline = KMeans(k=3, seed=1, max_iter=25).fit(x)
        set_config(memory_budget_hbm="3M")
        m = KMeans(k=3, seed=1, max_iter=25).fit(x)
        assert m.summary.route["route"] == mb.ROUTE_STREAMED
        assert m.summary.route["degraded_scale"] is True
        assert getattr(m.summary, "streamed", False)
        # the streamed route converges to the same optimum on blobs
        # (init RNG streams legitimately differ: reservoir vs in-memory)
        np.testing.assert_allclose(
            m.summary.training_cost, baseline.summary.training_cost,
            rtol=1e-4,
        )

    def test_strict_raises_at_fit_entry(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(memory_budget_hbm="3M", scale_policy="strict")
        with pytest.raises(mb.BudgetError, match="strict"):
            KMeans(k=3, seed=1, max_iter=2).fit(_blobs(rng))

    def test_pin_streamed_executes_streamed(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(scale_policy="pin:streamed")
        m = KMeans(k=3, seed=1, max_iter=2).fit(_blobs(rng))
        assert m.summary.route["route"] == mb.ROUTE_STREAMED
        assert m.summary.route["forced"] is True
        assert getattr(m.summary, "streamed", False)

    def test_pca_and_als_summaries_carry_route(self, rng):
        from oap_mllib_tpu.models.als import ALS
        from oap_mllib_tpu.models.pca import PCA

        p = PCA(k=2).fit(_blobs(rng))
        assert p.summary["route"]["route"] == mb.ROUTE_IN_MEMORY
        u = rng.integers(30, size=300)
        i = rng.integers(20, size=300)
        r = rng.random(300).astype(np.float32)
        a = ALS(rank=3, max_iter=1, seed=3).fit(u, i, r)
        # the suite mesh has 8 virtual devices -> the block route is
        # both natural and chosen; a 1-device world fits in-memory
        from oap_mllib_tpu.parallel.mesh import get_mesh

        mesh = get_mesh()
        expected = (
            mb.ROUTE_STREAMED_BLOCK
            if mesh.shape[mesh.axis_names[0]] > 1 else mb.ROUTE_IN_MEMORY
        )
        assert a.summary["route"]["route"] == expected
        assert a.summary["route"]["natural"] == expected

    def test_scale_policy_typo_raises_at_fit(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(scale_policy="bogus")
        with pytest.raises(ValueError, match="scale_policy"):
            KMeans(k=2, max_iter=1).fit(_blobs(rng))

    def test_route_span_node_annotated(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        m = KMeans(k=3, seed=1, max_iter=2).fit(_blobs(rng))
        route_span = m.summary.timings.root.node("route")
        assert route_span.attrs["route"] == m.summary.route["route"]


class TestCalibration:
    def test_estimate_vs_actual_cross_check_on_real_fit(self, rng):
        """A streamed fit records the observed bytes/row next to the
        planner's estimate, and the two agree within the calibration
        clamp (the estimate is analytic, not a guess)."""
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _blobs(rng, n=512, d=6)
        m = KMeans(k=3, seed=1, max_iter=3).fit(
            ChunkSource.from_array(x, chunk_rows=128)
        )
        r = m.summary.route
        assert r["actual_bytes_staged"] > 0
        assert r["staged_bytes_per_row"] > 0
        ratio = r["staged_bytes_per_row"] / r["estimated_bytes_per_row"]
        assert 0.25 <= ratio <= 4.0
        assert 0.25 <= r["calibration"] <= 4.0
        # the EMA moved off 1.0 toward the observation
        assert mb.calibration_factor("kmeans") == pytest.approx(
            1.0 + 0.3 * (max(min(ratio, 4.0), 0.25) - 1.0), rel=1e-6
        )

    def test_calibration_scales_next_plan(self):
        mb._note_calibration("kmeans", 100.0, 200.0)  # ratio 2 -> EMA 1.3
        f = mb.calibration_factor("kmeans")
        assert f == pytest.approx(1.3)
        lo = mb.plan_kmeans(1_000, 8, 3, source_backing="memory",
                            chunk_rows=128)
        mb.reset_calibration()
        base = mb.plan_kmeans(1_000, 8, 3, source_backing="memory",
                              chunk_rows=128)
        est_cal = lo.estimate_for(mb.ROUTE_STREAMED).hbm_bytes
        est_base = base.estimate_for(mb.ROUTE_STREAMED).hbm_bytes
        assert est_cal == pytest.approx(est_base * f, rel=0.01)


class TestMetricsSurface:
    def test_route_metrics_fire(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        before = tm.family_total("oap_route_decisions_total")
        KMeans(k=3, seed=1, max_iter=1).fit(_blobs(rng))
        assert tm.family_total("oap_route_decisions_total") == before + 1

    def test_spill_metric_fires(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans
        from oap_mllib_tpu.utils import faults

        set_config(fault_spec="prefetch.stage:oomhost=1",
                   retry_backoff=0.001)
        faults.reset()
        before = tm.family_total("oap_route_spills_total")
        KMeans(k=3, seed=1, max_iter=2).fit(
            ChunkSource.from_array(_blobs(rng), chunk_rows=128)
        )
        assert tm.family_total("oap_route_spills_total") == before + 1
        set_config(fault_spec="")
        faults.reset()


class TestBeyondHostBudget:
    """The ISSUE 12 acceptance leg: a dataset whose STAGED footprint
    exceeds the configured host-RAM budget fits end-to-end from a
    disk-backed ChunkSource through the prefetch pipeline on all three
    estimators — parity <= 1e-5 vs the in-memory route on identical
    data, summary.route naming the decision and its inputs — and strict
    mode does NOT raise (the disk route genuinely fits the budget)."""

    def _make(self, rng, tmp_path):
        # 40k x 8 f32 = 1.28 MB dense: past the synthetic 1 MB host
        # budget, trivially within O(chunk) when disk-backed
        proto = rng.normal(size=(3, 8)).astype(np.float32) * 4.0
        x = (proto[rng.integers(3, size=40_000)]
             + rng.normal(size=(40_000, 8)).astype(np.float32) * 0.2)
        path = str(tmp_path / "big.npy")
        np.save(path, x)
        return x, path

    def test_kmeans_pca_als_fit_from_disk_under_host_budget(
        self, rng, tmp_path
    ):
        from oap_mllib_tpu.models.als import ALS
        from oap_mllib_tpu.models.kmeans import KMeans
        from oap_mllib_tpu.models.pca import PCA

        x, path = self._make(rng, tmp_path)
        km_mem = KMeans(k=3, seed=5, max_iter=15).fit(x)
        pca_mem = PCA(k=2).fit(x)
        u = rng.integers(50, size=3000).astype(np.float64)
        i = rng.integers(40, size=3000).astype(np.float64)
        r = rng.random(3000)
        tri = np.stack([u, i, r], axis=1)
        tri_path = str(tmp_path / "tri.npy")
        np.save(tri_path, tri)
        als_mem = ALS(rank=3, max_iter=2, seed=3).fit(
            u.astype(np.int64), i.astype(np.int64), r.astype(np.float32)
        )

        set_config(memory_budget_host="1M", scale_policy="strict")
        km = KMeans(k=3, seed=5, max_iter=15).fit(
            ChunkSource.from_npy(path, chunk_rows=4096)
        )
        assert km.summary.route["route"] == mb.ROUTE_STREAMED
        assert km.summary.route["budgets"]["host"] == 1 << 20
        np.testing.assert_allclose(
            km.summary.training_cost, km_mem.summary.training_cost,
            rtol=1e-5,
        )
        pca = PCA(k=2).fit(ChunkSource.from_npy(path, chunk_rows=4096))
        assert pca.summary["route"]["route"] == mb.ROUTE_STREAMED
        np.testing.assert_allclose(
            np.abs(pca.components_), np.abs(pca_mem.components_),
            atol=1e-5,
        )
        np.testing.assert_allclose(
            pca.explained_variance_, pca_mem.explained_variance_,
            atol=1e-5,
        )
        set_config(scale_policy="auto")  # ALS ingest keeps host O(nnz):
        # the triples materialize to host arrays (executor-partition
        # semantics), so strict under a 1 MB host budget rightly refuses
        als = ALS(rank=3, max_iter=2, seed=3).fit(
            ChunkSource.from_npy(tri_path, chunk_rows=1024)
        )
        assert als.summary["route"]["route"] in (
            mb.ROUTE_STREAMED, mb.ROUTE_STREAMED_BLOCK
        )
        np.testing.assert_allclose(
            als.user_factors_, als_mem.user_factors_, atol=1e-5,
            rtol=1e-5,
        )
