"""Float64 parity lane: the reference's K-Means/PCA kernels run in double
(KMeansDALImpl.cpp:32) and its parity suite asserts 1e-5 (IntelPCASuite).
With enable_x64 the TPU-native kernels hit the same bar (here: far past it,
since both sides are f64).  jax's x64 flag is process-global, so this lane
runs in a subprocess."""

import os
import subprocess
import sys
import textwrap

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

_SCRIPT = textwrap.dedent("""
    import sys
    sys.path.insert(0, %r)
    import os
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    if hasattr(jax.config, "jax_num_cpu_devices"):
        jax.config.update("jax_num_cpu_devices", 8)
    jax.config.update("jax_enable_x64", True)
    import numpy as np
    from oap_mllib_tpu.config import set_config
    set_config(enable_x64=True)

    rng = np.random.default_rng(11)

    # PCA: components must match the f64 NumPy oracle to 1e-9
    basis = rng.normal(size=(10, 10)) * np.linspace(3, 0.1, 10)
    x = rng.normal(size=(400, 10)) @ basis
    from oap_mllib_tpu import PCA
    m = PCA(k=4).fit(x)
    xc = x - x.mean(0)
    cov = xc.T @ xc / (len(x) - 1)
    vals, vecs = np.linalg.eigh(cov)
    vecs = vecs[:, ::-1]; vals = vals[::-1]
    np.testing.assert_allclose(
        np.abs(m.components_), np.abs(vecs[:, :4]), atol=1e-9)
    np.testing.assert_allclose(
        m.explained_variance_, vals[:4] / vals.sum(), atol=1e-12)

    # K-Means: fixed init, converged centers match f64 oracle to 1e-9
    from oap_mllib_tpu.ops.kmeans_ops import lloyd_run
    import jax.numpy as jnp
    blobs = rng.normal(size=(4, 6)) * 5
    data = blobs[rng.integers(4, size=500)] + rng.normal(size=(500, 6)) * 0.05
    init = data[rng.choice(500, 4, replace=False)]
    c, it, cost, _ = lloyd_run(
        jnp.asarray(data), jnp.ones(500), jnp.asarray(init), 60,
        jnp.asarray(1e-12))
    cc = init.copy()
    for _ in range(60):
        d2 = ((data[:, None] - cc[None]) ** 2).sum(-1)
        a = d2.argmin(1)
        new = np.stack([data[a == j].mean(0) if (a == j).any() else cc[j]
                        for j in range(4)])
        done = ((new - cc) ** 2).sum(1).max() <= 1e-24
        cc = new
        if done:
            break
    np.testing.assert_allclose(np.asarray(c), cc, atol=1e-9)
    assert np.asarray(c).dtype == np.float64
    print("X64_PARITY_OK")
""" % REPO)


def test_f64_parity_subprocess():
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # breaks the TPU plugin; subprocess uses CPU anyway
    out = subprocess.run(
        [sys.executable, "-c", _SCRIPT], capture_output=True, text=True,
        timeout=300, env=env,
    )
    assert "X64_PARITY_OK" in out.stdout, out.stdout + out.stderr
