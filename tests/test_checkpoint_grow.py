"""Growable checkpoint axes (ISSUE 20): a warm start may RESUME into a
grown user/item extent — the live-models delta path grows tables
between fits, and refusing the old checkpoint would throw away every
converged iteration.

Contracts under test:

- growable axes are excluded from the directory hash, so an old fit's
  checkpoint and a grown fit's land in the same directory;
- restore into a grown axis: old rows bit-identical, growth recorded
  in ``RestoreResult.grown`` (and ``summary["checkpoint"]["grown"]``),
  the grown tail of an ALS warm start at the deterministic init;
- a SHRUNK axis is rejected with a clear :class:`CheckpointError`
  (restored rows beyond the new extent would be silently dropped), as
  is a reordered/changed growable declaration;
- non-growable signature keys still match exactly;
- a fabricated 2-rank manifest restores into a grown single-process
  world (reshard + growth compose).
"""

from __future__ import annotations

import numpy as np
import pytest

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.fallback import als_np
from oap_mllib_tpu.models.als import ALS
from oap_mllib_tpu.utils import checkpoint as ckpt_mod
from oap_mllib_tpu.utils.checkpoint import CheckpointError


def _sig(n_users=40, n_items=30, rank=3):
    return {"rank": rank, "reg": 0.1, "n_users": n_users,
            "n_items": n_items}


GROWABLE = ("n_users", "n_items")


def _write(tmp_path, n_users=40, n_items=30, rank=3, step=4):
    set_config(checkpoint_dir=str(tmp_path))
    ck = ckpt_mod.Checkpointer(
        "als", _sig(n_users, n_items, rank), growable=GROWABLE
    )
    x = np.arange(n_users * rank, dtype=np.float32).reshape(n_users, rank)
    y = -np.arange(n_items * rank, dtype=np.float32).reshape(n_items, rank)
    ck._write_shard(step, {"x": x, "y": y}, {})
    ck._write_manifest(step, ["x", "y"], {}, {}, {})
    return x, y


class TestGrowableAxes:
    def test_growable_excluded_from_dir_hash(self, tmp_path):
        set_config(checkpoint_dir=str(tmp_path))
        a = ckpt_mod.Checkpointer("als", _sig(40, 30), growable=GROWABLE)
        b = ckpt_mod.Checkpointer("als", _sig(45, 33), growable=GROWABLE)
        assert a.dir == b.dir
        # a NON-growable key still separates directories
        c = ckpt_mod.Checkpointer(
            "als", _sig(40, 30, rank=4), growable=GROWABLE
        )
        assert c.dir != a.dir
        # and the no-growable form keeps its pre-existing naming
        d = ckpt_mod.Checkpointer("als", _sig(40, 30))
        assert d.dir != a.dir

    def test_growable_key_must_be_in_signature(self, tmp_path):
        set_config(checkpoint_dir=str(tmp_path))
        with pytest.raises(ValueError, match="growable"):
            ckpt_mod.Checkpointer(
                "als", _sig(), growable=("n_users", "n_rows")
            )

    def test_restore_into_grown_axis(self, tmp_path):
        x, y = _write(tmp_path, n_users=40, n_items=30)
        ck = ckpt_mod.Checkpointer(
            "als", _sig(45, 30), growable=GROWABLE
        )
        res = ck._load()
        assert res.found and res.grown == {"n_users": (40, 45)}
        got = ckpt_mod.factors_from_result(res, "x", 45)
        np.testing.assert_array_equal(got[:40], x)  # old rows bit-exact
        np.testing.assert_array_equal(got[40:], 0.0)  # caller fills init
        # unchanged axes restore with grown == {}
        same = ckpt_mod.Checkpointer(
            "als", _sig(40, 30), growable=GROWABLE
        )._load()
        assert same.found and same.grown == {}

    def test_grown_lands_in_summary_checkpoint(self, tmp_path):
        _write(tmp_path, n_users=40)
        ck = ckpt_mod.Checkpointer("als", _sig(44, 30), growable=GROWABLE)
        res = ck._load()
        ck._result = res
        summary: dict = {}
        ck.record(summary)
        assert summary["checkpoint"]["grown"] == {"n_users": [40, 44]}

    def test_shrunk_axis_rejected(self, tmp_path):
        _write(tmp_path, n_users=40)
        ck = ckpt_mod.Checkpointer("als", _sig(38, 30), growable=GROWABLE)
        with pytest.raises(CheckpointError, match="shrank"):
            ck._load()

    def test_growable_declaration_mismatch_rejected(self, tmp_path):
        _write(tmp_path)
        # the same dir reached with a REORDERED declaration must refuse
        ck = ckpt_mod.Checkpointer(
            "als", _sig(), growable=("n_items", "n_users")
        )
        ck.dir = ckpt_mod.Checkpointer(
            "als", _sig(), growable=GROWABLE
        ).dir
        with pytest.raises(CheckpointError, match="growable-axis"):
            ck._load()

    def test_fixed_key_mismatch_still_rejected(self, tmp_path):
        _write(tmp_path)
        ck = ckpt_mod.Checkpointer("als", _sig(), growable=GROWABLE)
        ck.signature = dict(_sig(), reg=0.2)
        with pytest.raises(CheckpointError, match="signature"):
            ck._load()

    def test_two_rank_manifest_restores_into_grown_world(self, tmp_path):
        """Reshard + growth compose: a 2-rank world's sharded user
        factors (rows 0-39) restore in THIS 1-process world into a
        45-row fit — old rows bit-identical, tail zero-filled for the
        caller's init pass."""
        set_config(checkpoint_dir=str(tmp_path))
        rank = 3
        ck = ckpt_mod.Checkpointer("als", _sig(40, 30), growable=GROWABLE)
        ck.world = 2
        vals = np.arange(120, dtype=np.float32).reshape(40, 3)
        for r in (0, 1):
            ck.rank = r
            ids = np.arange(20, dtype=np.int64) + 20 * r
            ck._write_shard(5, {}, {"x": (ids, vals[ids])})
        ck.rank = 0
        ck._write_manifest(
            5, [], {}, {"x": (np.arange(20), vals[:20])}, {}
        )
        grown = ckpt_mod.Checkpointer(
            "als", _sig(45, 30), growable=GROWABLE
        )
        res = grown._load()
        assert res.decision == "resharded" and res.old_world == 2
        assert res.grown == {"n_users": (40, 45)}
        got = ckpt_mod.factors_from_result(res, "x", 45)
        np.testing.assert_array_equal(got[:40], vals)
        np.testing.assert_array_equal(got[40:], 0.0)


class TestALSWarmStartGrown:
    def test_resume_into_grown_user_axis_end_to_end(self, tmp_path, rng=None):
        """An interrupted fit's checkpoint warm-starts a fit whose user
        axis GREW: restored rows continue bit-identically, the grown
        tail takes the deterministic init (what a from-scratch fit
        would have initialized those rows to)."""
        rng = np.random.default_rng(11)
        u = rng.integers(0, 40, size=2000)
        i = rng.integers(0, 30, size=2000)
        v = rng.normal(1.0, 0.5, size=2000).astype(np.float32)
        set_config(checkpoint_dir=str(tmp_path))
        est = dict(rank=3, max_iter=4, reg_param=0.1, seed=7,
                   num_user_blocks=1)
        base = ALS(**est).fit(u, i, v, n_users=40, n_items=30)
        # same data, grown user extent, SAME max_iter: the restore is
        # at the recorded step, so zero further iterations run — the
        # output IS the restored+grown state
        grown = ALS(**est).fit(u, i, v, n_users=45, n_items=30)
        assert grown.summary["checkpoint"]["grown"] == {
            "n_users": [40, 45]
        }
        np.testing.assert_array_equal(
            grown.user_factors_[:40], base.user_factors_
        )
        np.testing.assert_array_equal(
            grown.user_factors_[40:],
            als_np.init_factors_rows(40, 45, 3, 7),
        )
        np.testing.assert_array_equal(
            grown.item_factors_, base.item_factors_
        )

    def test_shrunk_fit_refused_under_require(self, tmp_path):
        rng = np.random.default_rng(11)
        u = rng.integers(0, 40, size=1500)
        i = rng.integers(0, 30, size=1500)
        v = rng.normal(1.0, 0.5, size=1500).astype(np.float32)
        set_config(checkpoint_dir=str(tmp_path))
        est = dict(rank=3, max_iter=3, reg_param=0.1, seed=7,
                   num_user_blocks=1)
        ALS(**est).fit(u, i, v, n_users=40, n_items=30)
        set_config(resume="require")
        with pytest.raises(CheckpointError, match="shrank"):
            ALS(**est).fit(u[u < 38], i[u < 38], v[u < 38],
                           n_users=38, n_items=30)
