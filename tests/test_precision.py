"""Mixed-precision compute policy (utils/precision.py + the threading
through every jitted fit entry): resolution/validation, bf16-vs-f32
parity on fixed seeds, staging-time casts in the prefetch pipeline, the
resilience ladder's f32-degradation rung, and summary/telemetry
exposure of the chosen policy."""

import jax.numpy as jnp
import numpy as np
import pytest

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.utils import precision as psn


def _blobs(rng, n=2048, d=16, k=4, spread=6.0, noise=0.2):
    proto = rng.normal(size=(k, d)).astype(np.float32) * spread
    x = (proto[rng.integers(k, size=n)]
         + rng.normal(size=(n, d)).astype(np.float32) * noise)
    return x


class TestResolution:
    def test_default_is_f32_with_configured_tier(self):
        pol = psn.resolve("kmeans")
        assert pol.name == "f32"
        assert pol.requested == "f32"
        assert pol.input_dtype == "float32"
        assert pol.accum_dtype == "float32"
        assert pol.dot_tier == "highest"  # matmul_precision default

    def test_explicit_tiers_resolve(self):
        for tier, in_dt in (("tf32", "float32"), ("bf16", "bfloat16")):
            set_config(compute_precision=tier)
            pol = psn.resolve("pca")
            assert pol.name == tier
            assert pol.input_dtype == in_dt
            assert pol.accum_dtype == "float32"

    def test_typo_raises(self):
        set_config(compute_precision="bf8")
        with pytest.raises(ValueError, match="compute_precision"):
            psn.resolve("kmeans")

    def test_per_algo_override_wins_and_validates(self):
        set_config(compute_precision="bf16", als_precision="f32")
        assert psn.resolve("als").name == "f32"
        assert psn.resolve("kmeans").name == "bf16"
        set_config(als_precision="bogus")
        with pytest.raises(ValueError, match="als_precision"):
            psn.resolve("als")

    def test_unknown_algo_raises(self):
        with pytest.raises(ValueError, match="unknown algorithm"):
            psn.resolve("svm")

    def test_typod_matmul_precision_raises_under_any_policy(self):
        set_config(compute_precision="bf16", matmul_precision="hihgest")
        with pytest.raises(ValueError, match="matmul_precision"):
            psn.resolve("kmeans")

    def test_auto_is_f32_without_fast_bf16_backend(self, monkeypatch):
        # the suite runs on CPU — auto must not downgrade where bf16
        # buys no throughput
        set_config(compute_precision="auto")
        assert psn.resolve("kmeans").name == "f32"
        # with a fast-bf16 backend, auto picks bf16 for every algorithm
        # with a registered parity bound (all three)
        monkeypatch.setattr(psn, "_fast_bf16_backend", lambda: True)
        for algo in psn.ALGOS:
            pol = psn.resolve(algo)
            assert pol.name == "bf16" and pol.requested == "auto"

    def test_x64_pins_f32(self, monkeypatch):
        monkeypatch.setattr(psn, "_fast_bf16_backend", lambda: True)
        set_config(compute_precision="bf16", enable_x64=True)
        pol = psn.resolve("pca")
        assert pol.name == "f32"
        assert pol.input_dtype == "float64"
        set_config(compute_precision="auto")
        assert psn.resolve("pca").name == "f32"

    def test_force_f32_scope_overrides(self):
        set_config(compute_precision="bf16")
        with psn.force_f32():
            assert psn.resolve("als").name == "f32"
        assert psn.resolve("als").name == "bf16"

    def test_reduced_active_tracks_attempt(self):
        psn.begin_attempt()
        assert not psn.reduced_active()
        psn.resolve("kmeans")  # f32 default
        assert not psn.reduced_active()
        set_config(compute_precision="tf32")
        psn.resolve("kmeans")
        assert psn.reduced_active()
        psn.begin_attempt()
        assert not psn.reduced_active()

    def test_kernel_tier_mapping(self):
        assert psn.kernel_tier("f32", "highest") == "highest"
        assert psn.kernel_tier("f32", "high") == "high"
        assert psn.kernel_tier("tf32", "highest") == "high"
        assert psn.kernel_tier("bf16", "highest") == "default"
        with pytest.raises(ValueError):
            psn.kernel_tier("fp8", "highest")

    def test_staging_dtype(self):
        import ml_dtypes

        assert psn.staging_dtype("f32", np.float32) == np.float32
        assert psn.staging_dtype("tf32", np.float32) == np.float32
        assert psn.staging_dtype("bf16", np.float32) == np.dtype(
            ml_dtypes.bfloat16
        )
        # the f64 lane never stages reduced
        assert psn.staging_dtype("bf16", np.float64) == np.float64


class TestPolicyDots:
    def test_pdot_f32_bitwise_matches_legacy(self, rng):
        a = jnp.asarray(rng.normal(size=(32, 16)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
        for tier in ("highest", "high", "default"):
            want = jnp.matmul(a, b, precision=psn.legacy_precision(tier))
            got = psn.pdot(a, b, "f32", tier)
            assert np.array_equal(np.asarray(want), np.asarray(got))

    def test_pdot_bf16_accumulates_f32(self, rng):
        a = jnp.asarray(rng.normal(size=(64, 128)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(128, 4)).astype(np.float32))
        out = psn.pdot(a, b, "bf16")
        assert out.dtype == jnp.float32
        ref = np.asarray(jnp.matmul(a, b, precision="highest"))
        # bf16 inputs: ~8-bit mantissa, f32 accumulation keeps the
        # contraction from compounding it
        rel = np.abs(np.asarray(out) - ref).max() / np.abs(ref).max()
        assert 0 < rel < 5e-2

    def test_pdot_accepts_bf16_staged_operands(self, rng):
        a32 = rng.normal(size=(16, 8)).astype(np.float32)
        a = jnp.asarray(a32).astype(jnp.bfloat16)
        b = jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32))
        out = psn.pdot(a, b, "bf16")
        assert out.dtype == jnp.float32
        # the f32 policy upcasts a stray bf16 operand rather than
        # promoting the whole dot to bf16
        out_f32 = psn.pdot(a, b, "f32", "highest")
        assert out_f32.dtype == jnp.float32

    def test_peinsum_f32_matches_legacy_highest(self, rng):
        a = jnp.asarray(rng.normal(size=(3, 4, 5)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(6, 4, 5)).astype(np.float32))
        want = jnp.einsum("agp,bgp->gab", a, b, precision="highest")
        got = psn.peinsum("agp,bgp->gab", a, b, "f32")
        assert np.array_equal(np.asarray(want), np.asarray(got))

    def test_upcast_noop_for_f32(self, rng):
        a = jnp.asarray(rng.normal(size=(4,)).astype(np.float32))
        assert psn.upcast(a) is a


class TestParity:
    """bf16 vs f32 on fixed seeds, within the registered bounds
    (dev/precision_gate.py runs the same checks on larger shapes)."""

    def test_kmeans_centroids_and_cost(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _blobs(rng)
        ref = KMeans(k=4, seed=7, max_iter=10).fit(x)
        set_config(compute_precision="bf16")
        bf = KMeans(k=4, seed=7, max_iter=10).fit(x)
        scale = float(np.abs(x).max())
        d2 = ((bf.cluster_centers_[:, None, :]
               - ref.cluster_centers_[None, :, :]) ** 2).sum(-1)
        cen = float(np.sqrt(d2.min(axis=1)).max()) / scale
        cost = abs(bf.summary.training_cost - ref.summary.training_cost)
        cost /= max(ref.summary.training_cost, 1e-30)
        b = psn.PARITY_BOUNDS["kmeans"]
        assert cen <= b["centroid_rel"], cen
        assert cost <= b["cost_rel"], cost

    def test_pca_subspace_and_ratios(self, rng):
        from oap_mllib_tpu.models.pca import PCA

        x = _blobs(rng)
        ref = PCA(k=3).fit(x)
        set_config(compute_precision="bf16")
        bf = PCA(k=3).fit(x)
        s = np.linalg.svd(ref.components_.T @ bf.components_,
                          compute_uv=False)
        angle = float(np.arccos(np.clip(s.min(), 0.0, 1.0)))
        ratio = float(np.abs(
            bf.explained_variance_ - ref.explained_variance_
        ).max())
        b = psn.PARITY_BOUNDS["pca"]
        assert angle <= b["subspace_rad"], angle
        assert ratio <= b["ratio_abs"], ratio

    def test_als_factors_and_predictions(self, rng):
        from oap_mllib_tpu.models.als import ALS

        nu, ni, nnz = 300, 200, 8000
        u = rng.integers(nu, size=nnz).astype(np.int64)
        i = rng.integers(ni, size=nnz).astype(np.int64)
        r = (rng.random(nnz) * 4 + 1).astype(np.float32)
        ref = ALS(rank=6, max_iter=4, seed=3, implicit_prefs=True,
                  alpha=10.0).fit(u, i, r)
        set_config(compute_precision="bf16")
        bf = ALS(rank=6, max_iter=4, seed=3, implicit_prefs=True,
                 alpha=10.0).fit(u, i, r)
        b = psn.PARITY_BOUNDS["als"]
        f_dev = float(np.abs(bf.user_factors_ - ref.user_factors_).max())
        f_dev /= max(float(np.abs(ref.user_factors_).max()), 1e-30)
        pref = ref.predict(u[:1000], i[:1000])
        pbf = bf.predict(u[:1000], i[:1000])
        rmse = float(np.sqrt(np.mean((pbf - pref) ** 2)))
        rmse /= max(float(np.sqrt(np.mean(pref ** 2))), 1e-30)
        assert f_dev <= b["factor_rel"], f_dev
        assert rmse <= b["rmse_rel"], rmse

    def test_f32_policy_is_bit_compatible(self, rng):
        """compute_precision='f32' must reproduce the default-argument
        (pre-policy) kernels EXACTLY — at the op level, where a silent
        numerics change would hide inside fit-level tolerance."""
        from oap_mllib_tpu.ops import kmeans_ops, pca_ops

        x = jnp.asarray(_blobs(rng, n=512))
        w = jnp.ones((512,), jnp.float32)
        c = jnp.asarray(np.asarray(x)[:4])
        for tier in ("highest", "high"):
            a = kmeans_ops._accumulate(x, w, c, tier, True)
            bb = kmeans_ops._accumulate(x, w, c, tier, True, "f32")
            for u, v in zip(a, bb):
                assert np.array_equal(np.asarray(u), np.asarray(v))
        cov_a, _ = pca_ops._covariance_jit(x, w, jnp.asarray(512.0), "highest")
        cov_b, _ = pca_ops._covariance_jit(
            x, w, jnp.asarray(512.0), "highest", "f32"
        )
        assert np.array_equal(np.asarray(cov_a), np.asarray(cov_b))

    def test_streamed_f32_matches_in_memory_contract(self, rng):
        """Streamed fits under the explicit f32 policy stay bit-identical
        to the default-config streamed fit (stage dtype unchanged)."""
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _blobs(rng, n=1024)
        src = ChunkSource.from_array(x, chunk_rows=256)
        ref = KMeans(k=4, seed=7, max_iter=5).fit(src)
        set_config(compute_precision="f32")
        f32 = KMeans(k=4, seed=7, max_iter=5).fit(src)
        assert np.array_equal(ref.cluster_centers_, f32.cluster_centers_)
        assert ref.summary.training_cost == f32.summary.training_cost


class TestStagingCasts:
    def test_streamed_chunks_stage_bf16(self, rng):
        from oap_mllib_tpu.data.prefetch import PrefetchStats
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.ops import stream_ops

        x = _blobs(rng, n=512)
        src = ChunkSource.from_array(x, chunk_rows=256)
        stats = PrefetchStats()
        sd = psn.staging_dtype("bf16", np.float32)
        with stream_ops._staged_chunks(src, None, np.float32, stats, sd) as pf:
            for host_chunk, n_valid, host_w, cj, wj in pf:
                assert cj.dtype == jnp.bfloat16
                assert wj.dtype == jnp.float32  # weights stay accum dtype
                # half the bytes of the f32 staging path per data chunk
                assert cj.nbytes * 2 == host_chunk.astype(np.float32).nbytes

    def test_streamed_chunks_stage_f32_by_default(self, rng):
        from oap_mllib_tpu.data.prefetch import PrefetchStats
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.ops import stream_ops

        x = _blobs(rng, n=512)
        src = ChunkSource.from_array(x, chunk_rows=256)
        stats = PrefetchStats()
        with stream_ops._staged_chunks(src, None, np.float32, stats) as pf:
            for _, _, _, cj, wj in pf:
                assert cj.dtype == jnp.float32

    def test_streamed_bf16_fit_within_bounds(self, rng):
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _blobs(rng, n=1024)
        src = ChunkSource.from_array(x, chunk_rows=256)
        ref = KMeans(k=4, seed=7, max_iter=8).fit(src)
        set_config(compute_precision="bf16")
        bf = KMeans(k=4, seed=7, max_iter=8).fit(src)
        assert bf.summary.precision == "bf16"
        cost = abs(bf.summary.training_cost - ref.summary.training_cost)
        cost /= max(ref.summary.training_cost, 1e-30)
        # the final cost pass re-stages at f32 (the user-facing objective
        # must not carry the cancellation of bf16-rounded inputs)
        assert cost <= psn.PARITY_BOUNDS["kmeans"]["cost_rel"]


class TestDegradationRung:
    def test_rung_unit(self):
        """resilient_fit: a NONFINITE fault under a reduced policy takes
        ONE f32 retry (inside force_f32) before the nonfinite_policy
        decision; at f32 the original raise semantics hold."""
        from oap_mllib_tpu.utils import resilience

        set_config(compute_precision="bf16", retry_backoff=0.001)
        seen = []

        def attempt(degraded):
            pol = psn.resolve("kmeans")
            seen.append(pol.name)
            if pol.name != "f32":
                raise resilience.NonFiniteError("bf16 overflow")
            return "ok"

        stats = resilience.ResilienceStats()
        out = resilience.resilient_fit("KMeans", attempt, None, stats=stats)
        assert out == "ok"
        assert seen == ["bf16", "f32"]
        assert stats.degradations == 1

    def test_rung_skipped_at_f32(self):
        """A fit already at f32 keeps the exact pre-policy semantics:
        NONFINITE + nonfinite_policy='raise' propagates immediately."""
        from oap_mllib_tpu.utils import resilience

        calls = []

        def attempt(degraded):
            psn.resolve("kmeans")  # f32 default
            calls.append(1)
            raise resilience.NonFiniteError("genuine f32 nonfinite")

        with pytest.raises(resilience.NonFiniteError):
            resilience.resilient_fit("KMeans", attempt, None)
        assert len(calls) == 1

    def test_rung_end_to_end_with_injected_fault(self, rng):
        """Injected 'nan' fault at the jitted-launch site under bf16:
        the fit completes ACCELERATED at f32, one degradation booked."""
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.models.kmeans import KMeans
        from oap_mllib_tpu.utils import faults

        x = _blobs(rng, n=1024)
        src = ChunkSource.from_array(x, chunk_rows=256)
        set_config(compute_precision="bf16",
                   fault_spec="fit.execute:nan=1", retry_backoff=0.001)
        faults.reset()
        m = KMeans(k=4, seed=7, max_iter=5).fit(src)
        assert m.summary.accelerated
        assert m.summary.precision == "f32"  # the rung's retry recorded
        assert m.summary.resilience["degradations"] == 1

    def test_nan_fault_kind_classifies_nonfinite(self):
        from oap_mllib_tpu.utils import faults, resilience

        exc = faults._make_fault(faults.KIND_NONFINITE, "fit.execute", 1)
        assert resilience.classify_fault(exc) == resilience.NONFINITE


class TestExposure:
    def test_summaries_and_span_attrs(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans
        from oap_mllib_tpu.models.pca import PCA

        x = _blobs(rng, n=512)
        set_config(compute_precision="tf32")
        m = KMeans(k=4, seed=7, max_iter=3).fit(x)
        assert m.summary.precision == "tf32"
        assert m.summary.timings.root.attrs["precision"] == "tf32"
        p = PCA(k=2).fit(x)
        assert p.summary["precision"] == "tf32"
        assert p.summary["timings"].root.attrs["precision"] == "tf32"

    def test_policy_rides_telemetry_export(self, rng, tmp_path):
        """The span-tree root's precision attr reaches the JSONL sink."""
        import json

        from oap_mllib_tpu.models.kmeans import KMeans

        log = tmp_path / "t.jsonl"
        set_config(compute_precision="bf16", telemetry_log=str(log))
        KMeans(k=4, seed=7, max_iter=3).fit(_blobs(rng, n=512))
        roots = [
            json.loads(line) for line in log.read_text().splitlines()
            if json.loads(line).get("path") == "kmeans.fit"
        ]
        assert roots and all(
            r["attrs"]["precision"] == "bf16" for r in roots
        )

    def test_als_summary_records_policy(self, rng):
        from oap_mllib_tpu.models.als import ALS

        u = rng.integers(50, size=1000).astype(np.int64)
        i = rng.integers(40, size=1000).astype(np.int64)
        r = (rng.random(1000) * 4 + 1).astype(np.float32)
        set_config(compute_precision="bf16")
        m = ALS(rank=4, max_iter=2, seed=3).fit(u, i, r)
        assert m.summary["precision"] == "bf16"

    def test_pallas_mode_aliases(self):
        # the alias table moved to the shared kernel-plane vocabulary
        # (ops/pallas/_tiers, ISSUE 9) so every kernel resolves policies
        # identically
        from oap_mllib_tpu.ops.pallas._tiers import check_mode

        assert check_mode("f32") == "highest"
        assert check_mode("tf32") == "high"
        assert check_mode("bf16") == "default"
        assert check_mode("highest") == "highest"
        with pytest.raises(ValueError, match="mode"):
            check_mode("fp8")
