"""Request-lifecycle tracing + SLO plane tests (ISSUE 19).

Contracts under test:

- the ledger's fixed stage schema sums to the request wall BY
  CONSTRUCTION (``cut`` closes full intervals; ``cut_flush`` clamps
  its parts to the flush interval) — fake-clock exact, live within 5%;
- sampling is a pure hash of the deterministic trace id (no RNG):
  identical decisions for identical ids, [0, 1] edge behavior, and a
  validated ``serve_trace_sample`` knob;
- armed tracing attaches a finalized ledger to every answered/shed
  future (``ledger_of``), books ``oap_serve_stage_seconds`` +
  ``oap_serve_traced_total``, and folds into
  ``serving_summary()["attribution"]``; disarmed, ``begin`` returns
  None and every hook is a miss;
- OpenMetrics exemplars ride histogram bucket lines with spec
  escaping and round-trip through a parser of the exposition format;
- the SLO engine's multi-window burn rates move under an induced
  breach (fake clock), the breach flag needs BOTH windows, windows
  prune, and brownout/scale decisions RECORD the witnessed SLO state;
- ``/healthz`` gains the serving block (queue depth, brownout rung,
  pins, last shed, SLO) and ``/sloz`` serves the engine state.
"""

from __future__ import annotations

import re

import numpy as np
import pytest

from oap_mllib_tpu import serving
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.serving import registry, reqtrace, slo, traffic
from oap_mllib_tpu.telemetry import metrics as tm


@pytest.fixture(autouse=True)
def _clear_serving():
    from oap_mllib_tpu.serving import ha

    registry.clear()
    traffic._reset_for_tests()
    ha._reset_for_tests()
    reqtrace._reset_for_tests()
    slo._reset_for_tests()
    yield
    registry.clear()
    traffic._reset_for_tests()
    ha._reset_for_tests()
    reqtrace._reset_for_tests()
    slo._reset_for_tests()


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class SpyHandle:
    kind = "spy"

    def predict_many(self, batches):
        return [np.full(b.shape[0], b.shape[0], np.int32) for b in batches]


class TestSampling:
    def test_trace_id_deterministic_and_rank_tagged(self):
        assert reqtrace.make_trace_id(3, 7) == "03-00000007"
        assert reqtrace.make_trace_id(3, 7) == reqtrace.make_trace_id(3, 7)
        assert reqtrace.make_trace_id(0, 7) != reqtrace.make_trace_id(1, 7)

    def test_sampling_is_pure_hash_of_the_id(self):
        ids = [reqtrace.make_trace_id(r, s)
               for r in range(3) for s in range(300)]
        first = [reqtrace.is_sampled(i, 0.37) for i in ids]
        again = [reqtrace.is_sampled(i, 0.37) for i in ids]
        assert first == again
        frac = sum(first) / len(first)
        assert 0.2 < frac < 0.55  # hash is not degenerately skewed

    def test_sampling_edges(self):
        tid = reqtrace.make_trace_id(0, 1)
        assert reqtrace.is_sampled(tid, 1.0) is True
        assert reqtrace.is_sampled(tid, 0.0) is False

    def test_knob_validated_at_begin(self):
        set_config(serve_trace_sample=1.5)
        with pytest.raises(ValueError, match="serve_trace_sample"):
            reqtrace.begin(0.0, 0, 0, 0.0)

    def test_disarmed_begin_returns_none(self):
        assert reqtrace.begin(0.0, 0, 0, 0.0) is None


class TestLedger:
    def _ledger(self, t0=100.0):
        set_config(serve_trace_sample=1.0)
        return reqtrace.begin(t0, 0, 5, 50.0)

    def test_cuts_sum_to_wall_exactly(self):
        lg = self._ledger(100.0)
        lg.cut("admission", 100.25)
        lg.cut("queue_wait", 101.0)
        lg.cut("batch_form", 101.125)
        lg.cut_flush(102.0, pad_s=0.25, compile_s=0.5)
        reqtrace.finalize(lg, "answered", 102.5, model="kmeans")
        assert lg.wall_s == pytest.approx(2.5)
        assert lg.stage_sum() == pytest.approx(lg.wall_s)
        assert lg.stages["admission"] == pytest.approx(0.25)
        assert lg.stages["queue_wait"] == pytest.approx(0.75)
        assert lg.stages["bucket_pad"] == pytest.approx(0.25)
        assert lg.stages["compile"] == pytest.approx(0.5)
        assert lg.stages["execute"] == pytest.approx(0.125)
        assert lg.stages["dispatch"] == pytest.approx(0.5)

    def test_cut_flush_clamps_parts_to_the_interval(self):
        """Measurement skew (pad + compile claiming more than the
        flush wall) must not break the sum-to-wall invariant."""
        lg = self._ledger(0.0)
        lg.cut("queue_wait", 1.0)
        lg.cut_flush(2.0, pad_s=5.0, compile_s=5.0)
        assert lg.stages["bucket_pad"] == pytest.approx(1.0)
        assert lg.stages["compile"] == pytest.approx(0.0)
        assert lg.stages["execute"] == pytest.approx(0.0)
        reqtrace.finalize(lg, "answered", 2.0)
        assert lg.stage_sum() == pytest.approx(lg.wall_s)

    def test_finalize_is_idempotent(self):
        lg = self._ledger(0.0)
        reqtrace.finalize(lg, "answered", 1.0)
        reqtrace.finalize(lg, "failed", 9.0)  # the race loser is a no-op
        assert lg.outcome == "answered"
        assert lg.wall_s == pytest.approx(1.0)

    def test_unknown_outcome_classifies_as_failed(self):
        lg = self._ledger(0.0)
        reqtrace.finalize(lg, "exploded", 1.0)
        assert lg.outcome == "failed"

    def test_record_schema_is_fixed(self):
        lg = self._ledger(10.0)
        lg.event("retry", "n=1", 10.5)
        reqtrace.finalize(lg, "answered", 11.0)
        rec = lg.as_record()
        assert set(rec["stages"]) == set(reqtrace.STAGES)
        for key in ("trace_id", "seq", "rank", "deadline_ms", "sampled",
                    "t0", "wall_s", "outcome", "model", "retries",
                    "events"):
            assert key in rec
        assert rec["events"][0]["kind"] == "retry"

    def test_finalize_books_histograms_and_outcome_counter(self):
        before = tm.family_total("oap_serve_traced_total")
        lg = self._ledger(0.0)
        lg.cut("queue_wait", 0.5)
        reqtrace.finalize(lg, "answered", 1.0)
        assert tm.family_total("oap_serve_traced_total") == before + 1
        q = reqtrace.stage_quantiles()
        assert q["queue_wait"]["count"] >= 1
        assert q["dispatch"]["count"] >= 1


class TestAttach:
    def test_notes_fold_into_attached_flush(self):
        set_config(serve_trace_sample=1.0)
        lg = reqtrace.begin(0.0, 0, 1, 0.0)
        with reqtrace.attach([lg, None]) as att:
            reqtrace.note_flush("bucket_pad", 0.25)
            reqtrace.note_flush("bucket_pad", 0.25)
            reqtrace.note_event("ring_hop", "hop=0 block=1", 0.5)
            assert reqtrace.exemplar_trace_id() == lg.ctx.trace_id
            assert att.flush_notes() == {"bucket_pad": 0.5}
        assert lg.events[0]["kind"] == "ring_hop"
        assert reqtrace.current_ledgers() == []

    def test_misses_outside_attach_are_noops(self):
        reqtrace.note_flush("bucket_pad", 1.0)
        reqtrace.note_event("ring_hop", "", 0.0)
        assert reqtrace.exemplar_trace_id() is None
        assert reqtrace.current_ledgers() == []


class TestTrafficIntegration:
    def test_answered_future_carries_finalized_ledger(self):
        clock = FakeClock(100.0)
        q = serving.TrafficQueue(SpyHandle(), start=False, clock=clock)
        set_config(serve_trace_sample=1.0)
        f = q.submit(np.zeros((4, 3), np.float32), deadline_ms=60_000)
        clock.advance(0.5)
        q.pump()
        q.close()
        lg = reqtrace.ledger_of(f)
        assert lg is not None
        assert lg.outcome == "answered"
        assert lg.model == "spy"
        assert lg.stage_sum() == pytest.approx(lg.wall_s)
        assert lg.stages["queue_wait"] == pytest.approx(0.5)

    def test_live_storm_ledgers_cover_wall_within_5pct(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        x = rng.normal(size=(400, 8)).astype(np.float32)
        handle = serving.serve(
            KMeans(k=3, seed=0, init_mode="random", max_iter=2).fit(x)
        )
        set_config(serve_trace_sample=1.0)
        with serving.TrafficQueue(handle) as q:
            futs = [
                q.submit(x[: int(s)], deadline_ms=60_000)
                for s in rng.integers(5, 128, size=20)
            ]
            for f in futs:
                f.result(timeout=60)
        for f in futs:
            lg = reqtrace.ledger_of(f)
            assert lg is not None and lg.outcome == "answered"
            assert abs(lg.stage_sum() - lg.wall_s) <= max(
                0.05 * lg.wall_s, 1e-6
            )
        attr = reqtrace.attribution_block()
        assert attr["traced"] >= 20
        assert 0.95 <= attr["coverage"] <= 1.05
        summ = serving.serving_summary()
        assert summ["attribution"]["traced"] >= 20

    def test_deadline_shed_finalizes_ledger_as_shed(self):
        clock = FakeClock(0.0)
        q = serving.TrafficQueue(SpyHandle(), start=False, clock=clock)
        set_config(serve_trace_sample=1.0)
        f = q.submit(np.zeros((4, 3), np.float32), deadline_ms=1.0)
        clock.advance(10.0)
        q.pump()
        q.close()
        assert isinstance(f.exception(), serving.ShedError)
        lg = reqtrace.ledger_of(f)
        assert lg is not None and lg.outcome == "shed"
        assert lg.stage_sum() == pytest.approx(lg.wall_s)

    def test_disarmed_future_has_no_ledger(self):
        q = serving.TrafficQueue(SpyHandle(), start=False)
        f = q.submit(np.zeros((4, 3), np.float32))
        q.pump()
        q.close()
        assert reqtrace.ledger_of(f) is None
        assert reqtrace.attribution_block() == {}


class TestExemplars:
    # the OpenMetrics exemplar suffix: `` # {labels} value`` after a
    # bucket line — this regex is the round-trip parser
    _EX = re.compile(
        r'^(?P<name>\w+_bucket)\{(?P<labels>[^}]*)\} (?P<count>\d+)'
        r'(?: # \{(?P<exlabels>[^}]*)\} (?P<exvalue>\S+))?$'
    )

    def test_exemplar_rides_the_bucket_line(self):
        h = tm.histogram("test_ex_seconds", {"stage": "execute"})
        h.observe(0.003, exemplar={"trace_id": "00-0000002a"})
        text = tm.render_prometheus()
        lines = [ln for ln in text.splitlines()
                 if ln.startswith("test_ex_seconds_bucket") and "#" in ln]
        assert len(lines) == 1  # latest-wins, exactly one bucket pinned
        m = self._EX.match(lines[0])
        assert m is not None, lines[0]
        assert 'trace_id="00-0000002a"' in m.group("exlabels")
        assert float(m.group("exvalue")) == pytest.approx(0.003)

    def test_exemplar_labels_are_spec_escaped_and_round_trip(self):
        h = tm.histogram("test_ex_escape_seconds")
        raw = 'id "quoted" back\\slash\nnewline'
        h.observe(0.001, exemplar={"trace_id": raw})
        text = tm.render_prometheus()
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("test_ex_escape_seconds_bucket") and "#" in ln
        )
        m = self._EX.match(line)
        assert m is not None, line
        body = m.group("exlabels")
        _, _, escaped = body.partition('="')
        escaped = escaped[:-1]  # strip the closing quote
        unescaped = (
            escaped.replace(r"\n", "\n").replace(r"\"", '"')
            .replace(r"\\", "\\")
        )
        assert unescaped == raw

    def test_latest_observation_wins_per_bucket(self):
        h = tm.histogram("test_ex_latest_seconds")
        h.observe(0.002, exemplar={"trace_id": "a"})
        h.observe(0.002, exemplar={"trace_id": "b"})
        text = tm.render_prometheus()
        assert 'trace_id="b"' in text
        assert 'trace_id="a"' not in text

    def test_plus_inf_bucket_carries_exemplars(self):
        h = tm.histogram("test_ex_inf_seconds")
        h.observe(1e9, exemplar={"trace_id": "huge"})
        line = next(
            ln for ln in tm.render_prometheus().splitlines()
            if 'le="+Inf"' in ln and ln.startswith("test_ex_inf")
        )
        assert 'trace_id="huge"' in line

    def test_untraced_histograms_render_unchanged(self):
        h = tm.histogram("test_ex_off_seconds")
        h.observe(0.001)
        assert h.exemplars is None
        for ln in tm.render_prometheus().splitlines():
            if ln.startswith("test_ex_off_seconds_bucket"):
                assert "#" not in ln

    def test_request_histogram_pins_sampled_trace_ids(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        x = rng.normal(size=(200, 6)).astype(np.float32)
        handle = serving.serve(
            KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(x)
        )
        set_config(serve_trace_sample=1.0)
        with serving.TrafficQueue(handle) as q:
            q.submit(x[:32], deadline_ms=60_000).result(timeout=60)
        text = tm.render_prometheus()
        stage_ex = [
            ln for ln in text.splitlines()
            if ln.startswith("oap_serve_stage_seconds_bucket")
            and "trace_id=" in ln
        ]
        assert stage_ex, "no exemplars on the stage histograms"


class TestSLOEngine:
    def _engine(self, clock, p99_ms=100.0, availability=0.99,
                window_s=600.0):
        return slo.SLOEngine(p99_ms, availability, window_s, clock=clock)

    def test_healthy_baseline_burns_nothing(self):
        clock = FakeClock()
        eng = self._engine(clock)
        for _ in range(100):
            clock.advance(0.1)
            eng.observe(0.010, ok=True)
        assert eng.burn_rate(eng.fast_window_s) == 0.0
        assert eng.budget_remaining() == 1.0
        assert eng.state()["breach"] is False

    def test_breach_moves_both_windows_and_flag(self):
        clock = FakeClock()
        eng = self._engine(clock)
        for _ in range(100):
            clock.advance(0.1)
            eng.observe(0.010, ok=True)
        for _ in range(50):  # every request blows the 100 ms target
            clock.advance(0.1)
            eng.observe(0.500, ok=True)
        st = eng.state()
        assert st["burn_rate_fast"] > 1.0
        assert st["burn_rate_slow"] > 1.0
        assert st["breach"] is True
        assert st["error_budget_remaining"] < 1.0
        assert tm.family_total("oap_slo_burn_rate") > 1.0

    def test_failures_are_bad_regardless_of_wall(self):
        clock = FakeClock()
        eng = self._engine(clock)
        eng.observe(0.001, ok=False)
        assert eng.bad == 1

    def test_breach_needs_both_windows(self):
        """Old badness outside the fast window burns the slow window
        only — no page."""
        clock = FakeClock()
        eng = self._engine(clock)  # fast window = 50 s
        for _ in range(20):
            clock.advance(0.1)
            eng.observe(0.500, ok=True)  # burst of bad
        clock.advance(60.0)  # bad burst ages out of the fast window
        for _ in range(20):
            clock.advance(0.1)
            eng.observe(0.010, ok=True)
        st = eng.state()
        assert st["burn_rate_slow"] > 1.0
        assert st["burn_rate_fast"] < 1.0
        assert st["breach"] is False

    def test_windows_prune_old_samples(self):
        clock = FakeClock()
        eng = self._engine(clock, window_s=10.0)
        for _ in range(5):
            clock.advance(0.1)
            eng.observe(0.500, ok=True)
        clock.advance(100.0)
        assert eng.burn_rate(eng.window_s) == 0.0
        assert eng.budget_remaining() == 1.0
        assert len(eng._samples) == 0  # pruned, not just filtered

    def test_knob_validation(self):
        set_config(serve_slo_availability=1.5, serve_slo_p99_ms=10.0)
        with pytest.raises(ValueError, match="serve_slo_availability"):
            slo.engine()

    def test_singleton_rebuilds_on_knob_change(self):
        set_config(serve_slo_p99_ms=100.0)
        e1 = slo.engine()
        set_config(serve_slo_p99_ms=200.0)
        e2 = slo.engine()
        assert e1 is not e2 and e2.p99_ms == 200.0
        assert slo.engine() is e2

    def test_disarmed_surface(self):
        assert slo.engine() is None
        assert slo.brief() == {}
        assert slo.summary_block() == {}
        assert slo.state() == {"armed": False}
        assert slo.slo_state() == {"armed": False}
        slo.observe_request(99.0, ok=False)  # one config check, no-op


class TestDecisionRecords:
    def _arm_breach(self):
        set_config(serve_slo_p99_ms=100.0, serve_slo_availability=0.99,
                   serve_slo_window_s=600.0)
        for _ in range(10):
            slo.observe_request(0.5, ok=False)

    def test_brownout_steps_record_slo_state(self):
        self._arm_breach()
        bc = serving.BrownoutController("auto")
        for _ in range(12):
            bc.observe(200, 100)
        assert bc.steps
        for step in bc.steps:
            assert step["slo"]["breach"] is True

    def test_scale_decisions_record_slo_state(self):
        self._arm_breach()
        sc = serving.ScaleController(1)
        d = sc.observe(queue_depth=3)
        assert d["slo"]["burn_rate_fast"] > 1.0
        assert d["slo"]["breach"] is True

    def test_disarmed_decisions_stay_clean(self):
        sc = serving.ScaleController(1)
        assert "slo" not in sc.observe(queue_depth=0)

    def test_traced_requests_feed_the_engine(self):
        set_config(serve_trace_sample=1.0, serve_slo_p99_ms=1000.0)
        q = serving.TrafficQueue(SpyHandle(), start=False)
        q.submit(np.zeros((4, 3), np.float32), deadline_ms=60_000)
        q.pump()
        q.close()
        eng = slo.engine()
        assert eng is not None and eng.total >= 1


class TestHealthSurfaces:
    def test_serving_health_block_fields(self):
        set_config(serve_slo_p99_ms=100.0)
        block = serving.serving_health_block()
        assert block["queue_depth"] == 0
        assert block["in_flight"] == 0
        assert block["pinned_models"] == 0
        assert block["brownout_rung"] == "off"
        assert "last_shed" not in block
        assert "burn_rate_fast" in block["slo"]

    def test_last_shed_reason_and_age_surface(self):
        set_config(serve_queue_depth=1)
        q = serving.TrafficQueue(SpyHandle(), start=False)
        q.submit(np.zeros((2, 3), np.float32))
        with pytest.raises(serving.ShedError):
            q.submit(np.zeros((2, 3), np.float32))
        q.close()
        block = serving.serving_health_block()
        assert block["last_shed"]["reason"] == "queue_full"
        assert block["last_shed"]["age_s"] >= 0.0

    def test_healthz_payload_carries_serving_block(self):
        from oap_mllib_tpu.telemetry import fleet

        payload = fleet._healthz_payload()
        assert "serving" in payload
        assert "queue_depth" in payload["serving"]

    def test_sloz_payload_tracks_engine_state(self):
        from oap_mllib_tpu.telemetry import fleet

        assert fleet._sloz_payload() == {"armed": False}
        set_config(serve_slo_p99_ms=100.0)
        slo.observe_request(0.5, ok=False)
        payload = fleet._sloz_payload()
        assert payload["armed"] is True
        assert payload["lifetime_requests"] >= 1

    def test_sloz_endpoint_served_next_to_metrics(self):
        import json
        import urllib.request

        from oap_mllib_tpu.parallel.bootstrap import free_port
        from oap_mllib_tpu.telemetry import fleet

        port = free_port("127.0.0.1", 9500)
        set_config(serve_slo_p99_ms=100.0, metrics_port=port)
        assert fleet.maybe_serve() == port
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/sloz", timeout=10
            ) as resp:
                payload = json.loads(resp.read())
            assert payload["armed"] is True
        finally:
            fleet.stop_server()
