"""Supervisor units (ISSUE 10, utils/supervisor.py): launch/classify/
relaunch/shrink with tiny jax-free subprocess workers, so the whole
policy surface is asserted on any host — the real jax-world drills ride
dev/chaos_gate.py and the pseudo-cluster legs."""

import os
import sys

import pytest

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.utils import recovery
from oap_mllib_tpu.utils.supervisor import Attempt, RankExit, Supervisor

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _script_argv(script: str):
    """build_argv for a tiny inline-python worker: argv[1:] =
    rank world coord attempt."""

    def build(rank, world, coord, attempt):
        return [sys.executable, "-c", script, str(rank), str(world),
                coord, str(attempt)]

    return build


def _mk(tmp_path, script, world=2, **kw):
    kw.setdefault("restart_backoff", 0.01)
    kw.setdefault("grace_s", 5.0)
    kw.setdefault("attempt_timeout", 60.0)
    return Supervisor(
        _script_argv(script), world, str(tmp_path / "sideband"),
        env={**os.environ, "PYTHONPATH": _REPO}, **kw
    )


class TestHappyPath:
    def test_clean_world_no_relaunch(self, tmp_path):
        sup = _mk(tmp_path, "print('RESULT ok')", restart_budget=3)
        s = sup.run()
        assert s["ok"] and s["relaunches"] == 0 and s["shrinks"] == 0
        assert s["final_world"] == 2
        assert all("RESULT ok" in o for o in s["outputs"])
        assert [e["classification"] for e in s["attempts"][0]["exits"]] == [
            "ok", "ok"
        ]

    def test_invalid_world_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="world"):
            _mk(tmp_path, "pass", world=0)

    def test_config_defaults_flow(self, tmp_path):
        set_config(restart_budget=7, restart_backoff=0.25, shrink_after=4)
        sup = Supervisor(
            _script_argv("pass"), 1, str(tmp_path / "sb"),
        )
        assert sup.restart_budget == 7
        assert sup.restart_backoff == 0.25
        assert sup.shrink_after == 4


# worker: rank 1 fails until a marker file exists (attempt 0 fails,
# attempt 1 succeeds) — the transient-host relaunch scenario
_FLAKY = """
import os, sys
rank, world, coord, attempt = sys.argv[1:5]
marker = os.environ["FLAKY_MARKER"]
if rank == "1" and not os.path.exists(marker):
    open(marker, "w").close()
    sys.exit(1)
print("RESULT attempt=" + attempt)
"""


class TestRelaunch:
    def test_fail_then_succeed_consumes_one_restart(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv("FLAKY_MARKER", str(tmp_path / "marker"))
        sup = _mk(tmp_path, _FLAKY, restart_budget=3)
        s = sup.run()
        assert s["ok"] and s["relaunches"] == 1 and s["shrinks"] == 0
        assert [a["ok"] for a in s["attempts"]] == [False, True]
        assert s["attempts"][0]["culprit"] == 1
        # the relaunched attempt index reached the workers (resume keying)
        assert any("attempt=1" in o for o in s["outputs"])

    def test_budget_exhausted_reports_not_ok(self, tmp_path):
        sup = _mk(tmp_path, "import sys; sys.exit(2)", world=1,
                  restart_budget=2)
        s = sup.run()
        assert not s["ok"]
        assert s["relaunches"] == 2  # the budget, fully spent
        assert len(s["attempts"]) == 3  # initial + 2 relaunches
        assert all(not a["ok"] for a in s["attempts"])

    def test_stale_crash_records_cleared_between_attempts(self, tmp_path,
                                                          monkeypatch):
        """A record from attempt N must not poison attempt N+1."""
        monkeypatch.setenv("FLAKY_MARKER", str(tmp_path / "marker"))
        record_then_ok = _FLAKY.replace(
            'open(marker, "w").close()',
            'open(marker, "w").close()\n'
            '    import json\n'
            '    json.dump({"rank": 1, "fault_class": "oom"}, '
            'open(os.environ["OAP_MLLIB_TPU_CRASH_DIR"] '
            '+ "/crash.rank1.json", "w"))',
        )
        sup = _mk(tmp_path, record_then_ok, restart_budget=2)
        s = sup.run()
        assert s["ok"]
        # attempt 0 classified from the record, attempt 1 clean
        assert s["attempts"][0]["exits"][1]["classification"] == "oom"
        assert recovery.check_poison(sup.crash_dir, 99) is None


# worker: rank (world-1) dies whenever the world is multi-process —
# the repeatedly-bad-host scenario the shrink policy exists for
_BAD_LAST_RANK = """
import sys
rank, world = int(sys.argv[1]), int(sys.argv[2])
if world > 1 and rank == world - 1:
    sys.exit(3)
print("RESULT world=" + str(world))
"""


class TestShrink:
    def test_repeated_culprit_shrinks_world(self, tmp_path):
        sup = _mk(tmp_path, _BAD_LAST_RANK, world=2, restart_budget=4,
                  shrink_after=2)
        s = sup.run()
        assert s["ok"]
        assert s["final_world"] == 1 and s["shrinks"] == 1
        # two blamed failures at world 2, then the shrunken world passes
        assert [a["world"] for a in s["attempts"]] == [2, 2, 1]
        assert any("world=1" in o for o in s["outputs"])

    def test_shrink_after_one_is_immediate(self, tmp_path):
        sup = _mk(tmp_path, _BAD_LAST_RANK, world=3, restart_budget=4,
                  shrink_after=1)
        s = sup.run()
        assert s["ok"] and s["final_world"] == 1
        assert [a["world"] for a in s["attempts"]] == [3, 2, 1]
        assert s["shrinks"] == 2

    def test_world_never_shrinks_below_one(self, tmp_path):
        sup = _mk(tmp_path, "import sys; sys.exit(1)", world=1,
                  restart_budget=2, shrink_after=1)
        s = sup.run()
        assert not s["ok"] and s["final_world"] == 1 and s["shrinks"] == 0


class TestClassification:
    def test_signal_death_is_killed(self, tmp_path):
        script = """
import os, signal, sys
if sys.argv[1] == "0":
    os.kill(os.getpid(), signal.SIGKILL)
print("RESULT ok")
"""
        sup = _mk(tmp_path, script, world=2, restart_budget=0)
        s = sup.run()
        e = s["attempts"][0]["exits"][0]
        assert e["classification"] == "killed"
        assert e["returncode"] == -9
        assert s["attempts"][0]["culprit"] == 0

    def test_crash_record_class_wins_over_exit_code(self, tmp_path):
        script = """
import json, os, sys
if sys.argv[1] == "1":
    json.dump(
        {"rank": 1, "fault_class": "oom", "site": "als.fit",
         "last_checkpoint_step": 4},
        open(os.environ["OAP_MLLIB_TPU_CRASH_DIR"] + "/crash.rank1.json",
             "w"))
    sys.exit(1)
print("RESULT ok")
"""
        sup = _mk(tmp_path, script, world=2, restart_budget=0)
        s = sup.run()
        e = s["attempts"][0]["exits"][1]
        assert e["classification"] == "oom"
        assert e["record"]["site"] == "als.fit"
        assert e["record"]["last_checkpoint_step"] == 4

    def test_victims_are_not_culprits(self):
        """Timeout/peer-abort ranks are casualties of the real fault —
        blame must land on the killed/faulted rank so shrink excludes
        the right host."""
        att = Attempt(index=0, world=3, exits=[
            RankExit(0, 0, recovery.FAULT_TIMEOUT,
                     record={"fault_class": recovery.FAULT_TIMEOUT}),
            RankExit(1, -9, "killed"),
            RankExit(2, 0, recovery.FAULT_PEER_ABORT,
                     record={"fault_class": recovery.FAULT_PEER_ABORT}),
        ])
        assert att.culprit() == 1

    def test_all_victims_blames_signal_death(self):
        att = Attempt(index=0, world=2, exits=[
            RankExit(0, 0, recovery.FAULT_TIMEOUT,
                     record={"fault_class": recovery.FAULT_TIMEOUT}),
            RankExit(1, -9, recovery.FAULT_TIMEOUT,
                     record={"fault_class": recovery.FAULT_TIMEOUT}),
        ])
        assert att.culprit() == 1

    def test_chaos_reseeds_per_attempt(self, tmp_path, monkeypatch):
        """The deterministic kill schedule must MOVE on relaunch, or the
        resumed world dies at the same call forever."""
        monkeypatch.setenv("FLAKY_MARKER", str(tmp_path / "marker"))
        script = _FLAKY.replace(
            'print("RESULT attempt=" + attempt)',
            'print("RESULT chaos=" + os.environ["OAP_MLLIB_TPU_CHAOS"])',
        )
        sup = _mk(tmp_path, script, restart_budget=2,
                  chaos="5:0.01:kill:1")
        s = sup.run()
        assert s["ok"]
        assert any("chaos=6:0.01:kill:1" in o for o in s["outputs"])

    def test_telemetry_counters(self, tmp_path, monkeypatch):
        from oap_mllib_tpu.telemetry import metrics as tm

        monkeypatch.setenv("FLAKY_MARKER", str(tmp_path / "marker"))
        before = tm.counter("oap_recovery_relaunches_total").value
        hist = tm.histogram("oap_recovery_time_to_recovery_seconds")
        count_before = hist.count
        sup = _mk(tmp_path, _FLAKY, restart_budget=3)
        assert sup.run()["ok"]
        assert tm.counter("oap_recovery_relaunches_total"
                          ).value == before + 1
        assert hist.count == count_before + 1


class TestCapabilityReprobe:
    """PR 15 follow-on (ISSUE 16): a relaunched rank must re-measure its
    capability — the supervisor bumps Config.probe_epoch per attempt and
    every probe cache is keyed by it."""

    def test_worker_env_carries_probe_epoch(self, tmp_path):
        sup = _mk(tmp_path, "pass")
        assert sup._worker_env(0)["OAP_MLLIB_TPU_PROBE_EPOCH"] == "0"
        assert sup._worker_env(3)["OAP_MLLIB_TPU_PROBE_EPOCH"] == "3"

    def test_epoch_bump_invalidates_pinned_then_cleared_probe(self):
        """The regression: a capability cached before preemption (here a
        pinned sentinel) must NOT survive into the relaunched attempt's
        epoch — the next consult re-probes."""
        from oap_mllib_tpu.utils import dispatch

        dispatch._reset_probe_for_tests()
        try:
            # pre-preemption: a pinned capability, measured+cached at
            # epoch 0
            set_config(rank_capability="0.25")
            cap, origin = dispatch.rank_capability()
            assert (cap, origin) == (0.25, "pinned")
            dispatch._probe_cache[(0, 0)] = 0.25
            # the pin is cleared (relaunched host, fresh config) but the
            # stale measurement still answers at epoch 0
            set_config(rank_capability="")
            assert dispatch.throughput_probe() == 0.25
            # the supervisor's epoch bump invalidates it: fresh probe
            set_config(probe_epoch=1)
            fresh = dispatch.throughput_probe()
            assert fresh != 0.25
            assert (0, 1) in dispatch._probe_cache
        finally:
            dispatch._reset_probe_for_tests()

    def test_epoch_bump_invalidates_world_capability_cache(self):
        from oap_mllib_tpu.parallel import balance

        balance._reset_for_tests()
        try:
            set_config(rank_capability="0.5")
            cw0 = balance.world_capabilities(1)
            assert balance.world_capabilities(1) is cw0  # cached
            set_config(probe_epoch=2)
            cw1 = balance.world_capabilities(1)
            assert cw1 is not cw0  # fresh gather under the new epoch
        finally:
            balance._reset_for_tests()


# worker: every rank fails on attempt 0, succeeds after — so the
# supervisor reads the scale hint at the attempt boundary and sizes the
# relaunch from it
_FAIL_ONCE = """
import sys
rank, world, coord, attempt = sys.argv[1:5]
if attempt == "0":
    sys.exit(1)
print("RESULT world=" + world)
"""


class TestScaleHint:
    def _write_hint(self, sup, action):
        import json

        os.makedirs(sup.crash_dir, exist_ok=True)
        with open(os.path.join(sup.crash_dir,
                               "serve.scale.hint.json"), "w") as f:
            json.dump({"action": action, "replicas": 1,
                       "reason": "test"}, f)

    def test_scale_in_hint_sizes_next_world(self, tmp_path):
        sup = _mk(tmp_path, _FAIL_ONCE, world=2, restart_budget=2)
        self._write_hint(sup, "in")
        s = sup.run()
        assert s["ok"]
        assert s["final_world"] == 1
        assert [a["world"] for a in s["attempts"]] == [2, 1]
        assert [h["action"] for h in s["scale_hints"]] == ["in"]
        assert any("world=1" in o for o in s["outputs"])
        # read-and-remove: the hint sized ONE relaunch
        assert not os.path.exists(
            os.path.join(sup.crash_dir, "serve.scale.hint.json")
        )

    def test_scale_out_hint_capped_at_initial_world(self, tmp_path):
        sup = _mk(tmp_path, _FAIL_ONCE, world=2, restart_budget=2)
        self._write_hint(sup, "out")
        s = sup.run()
        assert s["ok"]
        # out from the provisioned size holds (host resources were
        # sized for the initial world) — but the hint is recorded
        assert s["final_world"] == 2
        assert [h["action"] for h in s["scale_hints"]] == ["out"]

    def test_hold_or_torn_hint_ignored(self, tmp_path):
        import json

        sup = _mk(tmp_path, "pass", world=1)
        os.makedirs(sup.crash_dir, exist_ok=True)
        path = os.path.join(sup.crash_dir, "serve.scale.hint.json")
        with open(path, "w") as f:
            json.dump({"action": "hold"}, f)
        assert sup._read_scale_hint() is None
        assert not os.path.exists(path)  # consumed either way
        with open(path, "w") as f:
            f.write("{torn")
        assert sup._read_scale_hint() is None
        assert not os.path.exists(path)
