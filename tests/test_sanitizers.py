"""Runtime sanitizer plane units (utils/sanitizers.py, ISSUE 7).

The pseudo-cluster suite drives the sanitizers across a REAL 2-process
world (tests/test_pseudo_cluster.py::TestSanitizerPlane); these units
cover the single-process mechanics — parsing, the guards, the retrace
watch, fingerprinting — plus the cross-rank divergence diagnostic with
the gather stubbed (so the message contract is pinned even on hosts
that cannot spawn multiprocess worlds)."""

import numpy as np
import pytest

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.utils import sanitizers as san


@pytest.fixture(autouse=True)
def _fresh_sanitizer_state():
    san._reset_for_tests()
    yield
    san._reset_for_tests()


class TestConfigSurface:
    def test_default_off(self):
        assert san.enabled_set() == frozenset()
        assert not san.enabled("collective")

    def test_parse_comma_set(self):
        set_config(sanitizers="collective, retrace")
        assert san.enabled_set() == {"collective", "retrace"}
        assert san.enabled("retrace") and not san.enabled("transfer")

    def test_typo_raises_naming_valid_set(self):
        """The fault_spec/kmeans_kernel contract: a sanitizer config
        that silently arms nothing defeats the point."""
        set_config(sanitizers="colective")
        with pytest.raises(ValueError, match="transfer"):
            san.enabled("collective")


class TestCollectiveFingerprint:
    def test_off_records_nothing(self):
        san.note_collective("psum", "data", (4, 4), "float32")
        assert san.fingerprint() == (0, san.fingerprint()[1])

    def test_sequence_and_fingerprint(self):
        set_config(sanitizers="collective")
        san.note_collective("psum", "data", (4, 4), "float32")
        san.note_collective("all_gather", "data", (8,), "float32")
        n, digest = san.fingerprint()
        assert n == 2
        # deterministic: same sequence -> same digest
        san._reset_for_tests()
        san.note_collective("psum", "data", (4, 4), "float32")
        san.note_collective("all_gather", "data", (8,), "float32")
        assert san.fingerprint() == (n, digest)

    def test_reduced_dtype_changes_fingerprint(self):
        """A cross-rank PRECISION-POLICY divergence (one rank staging
        bf16, another f32) must show in the fingerprint."""
        set_config(sanitizers="collective")
        san.note_collective("psum", "data", (4, 4), "float32")
        _, f32 = san.fingerprint()
        san._reset_for_tests()
        san.note_collective("psum", "data", (4, 4), "bfloat16")
        _, bf16 = san.fingerprint()
        assert f32 != bf16

    def test_divergence_diagnostic_names_both_ops(self, monkeypatch):
        """The hang-to-diagnostic conversion: with a peer's frame
        differing, note_collective must raise naming THIS rank's op and
        the first differing rank's op (gather stubbed — the real-world
        pairing is exercised by the pseudo-cluster suite)."""
        set_config(sanitizers="collective")
        monkeypatch.setattr(san, "_world", lambda: 2)
        peer = b"op:allgather_rows|data|(4, 4)|float32:full"

        def fake_gather(frame):
            return [frame.rstrip(b"\x00"), peer]

        monkeypatch.setattr(san, "_gather_frames", fake_gather)
        with pytest.raises(san.CollectiveDivergenceError) as ei:
            san.note_collective("allreduce_sum", "data", (4, 4), "float32")
        msg = str(ei.value)
        assert "allreduce_sum" in msg and "allgather_rows" in msg
        assert "rank 1" in msg

    def test_finalize_attaches_fingerprint_and_advances_window(self):
        set_config(sanitizers="collective")
        san.note_collective("psum", "data", (4, 4), "float32")
        summary = {}
        san.finalize_fit_sanitizers(summary)
        assert summary["sanitizers"]["enabled"] == ["collective"]
        assert summary["sanitizers"]["collective"]["ops"] == 1
        assert not summary["sanitizers"]["collective"]["world_checked"]
        # the next fit fingerprints only its own ops
        summary2 = {}
        san.finalize_fit_sanitizers(summary2)
        assert summary2["sanitizers"]["collective"]["ops"] == 0

    def test_finalize_tail_divergence_raises(self, monkeypatch):
        """The fit-boundary backstop: rank-differing (count, digest)
        frames at finalization raise instead of silently passing."""
        set_config(sanitizers="collective")
        monkeypatch.setattr(san, "_world", lambda: 2)
        monkeypatch.setattr(
            san, "_gather_frames",
            lambda frame: [frame.rstrip(b"\x00"), b"fit:7:deadbeef"],
        )
        san.note_collective("psum", "data", (4, 4), "float32",
                            crosscheck=False)
        with pytest.raises(san.CollectiveDivergenceError, match="deadbeef"):
            san.finalize_fit_sanitizers({})


class TestTransferSanitizer:
    def test_guarded_loop_catches_implicit_transfer(self):
        import jax.numpy as jnp

        from oap_mllib_tpu.data.prefetch import Prefetcher

        set_config(sanitizers="transfer")
        host = np.ones((4, 4), np.float32)
        dev = [jnp.ones((4, 4)) for _ in range(3)]
        with pytest.raises(Exception, match="[Dd]isallowed"):
            with Prefetcher(dev) as pf:
                for c in pf:
                    _ = c + host  # implicit host->device of the operand

    def test_off_by_default_loop_is_unguarded(self):
        import jax.numpy as jnp

        from oap_mllib_tpu.data.prefetch import Prefetcher

        host = np.ones((4, 4), np.float32)
        with Prefetcher([jnp.ones((4, 4))] * 2) as pf:
            for c in pf:
                _ = c + host  # fine: sanitizer off

    def test_allow_transfers_escape_hatch(self):
        import jax.numpy as jnp

        from oap_mllib_tpu.data.prefetch import Prefetcher

        set_config(sanitizers="transfer")
        host = np.ones((4, 4), np.float32)
        with Prefetcher([jnp.ones((4, 4))] * 2) as pf:
            for c in pf:
                with san.allow_transfers():  # the audited-site analog
                    _ = c + host

    def test_streamed_fit_runs_clean_under_guard(self, rng):
        """The live streamed paths must be implicit-transfer-free: a
        full streamed K-Means fit (k-means|| init included — its audited
        host-sync sites run under allow_transfers) succeeds with the
        guard armed, and matches the unguarded fit bit-for-bit."""
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.models.kmeans import KMeans

        x = rng.normal(size=(800, 8)).astype(np.float32)
        base = KMeans(k=4, seed=3, max_iter=4).fit(
            ChunkSource.from_array(x, chunk_rows=256))
        set_config(sanitizers="transfer")
        guarded = KMeans(k=4, seed=3, max_iter=4).fit(
            ChunkSource.from_array(x, chunk_rows=256))
        assert guarded.summary.training_cost == base.summary.training_cost

    def test_streamed_pca_and_als_clean_under_guard(self, rng):
        """Every other streamed route is guard-clean too: the streamed
        PCA moments and the streamed ALS edge uploads dispatch only
        staged device buffers inside their chunk loops."""
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.models.als import ALS
        from oap_mllib_tpu.models.pca import PCA

        set_config(sanitizers="transfer")
        x = rng.normal(size=(600, 8)).astype(np.float32)
        PCA(k=3).fit(ChunkSource.from_array(x, chunk_rows=256))
        u = rng.integers(40, size=900).astype(np.int64)
        i = rng.integers(30, size=900).astype(np.int64)
        r = (rng.random(900) * 4 + 1).astype(np.float32)
        triples = np.stack(
            [u.astype(np.float64), i.astype(np.float64),
             r.astype(np.float64)], axis=1)
        src = ChunkSource.from_array(triples, chunk_rows=256)
        ALS(rank=3, max_iter=2, seed=3).fit(src)


class TestRetraceSanitizer:
    def test_steady_state_scope_passes_warm(self):
        import jax
        import jax.numpy as jnp

        set_config(sanitizers="retrace")
        f = jax.jit(lambda a: a * 2)
        f(jnp.ones((3,)))  # warmup outside the scope
        with san.steady_state("warm"):
            f(jnp.ones((3,)))

    def test_steady_state_scope_catches_compile(self):
        import jax
        import jax.numpy as jnp

        set_config(sanitizers="retrace")
        f = jax.jit(lambda a: a * 3)
        f(jnp.ones((3,)))
        with pytest.raises(san.RetraceError, match="steady-state scope"):
            with san.steady_state("probe"):
                f(jnp.ones((7,)))  # new shape -> backend compile

    def test_prefetch_loop_catches_mid_pass_retrace(self):
        """The per-chunk contract: chunk 0 may compile (warmup), any
        later chunk that triggers a backend compile is a retrace — the
        exact bug class PR 6 fixed in parallel/shuffle.py, now witnessed
        at runtime."""
        import jax
        import jax.numpy as jnp

        from oap_mllib_tpu.data.prefetch import Prefetcher

        set_config(sanitizers="retrace")
        f = jax.jit(lambda a: a + 1)
        chunks = [jnp.ones((4,)), jnp.ones((4,)), jnp.ones((9,))]
        with pytest.raises(san.RetraceError, match="after warmup"):
            with Prefetcher(chunks) as pf:
                for c in pf:
                    f(c)  # chunk 2's new shape compiles mid-pass

    def test_prefetch_loop_clean_on_stable_shapes(self):
        import jax
        import jax.numpy as jnp

        from oap_mllib_tpu.data.prefetch import Prefetcher

        set_config(sanitizers="retrace")
        f = jax.jit(lambda a: a + 2)
        with Prefetcher([jnp.ones((4,))] * 4) as pf:
            for c in pf:
                f(c)

    def test_streamed_fit_is_retrace_free(self, rng):
        """Steady-state streamed passes reuse one compiled program per
        pass: the whole fit runs under the retrace sanitizer without a
        finding."""
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(sanitizers="retrace")
        x = rng.normal(size=(800, 8)).astype(np.float32)
        KMeans(k=4, seed=3, max_iter=4).fit(
            ChunkSource.from_array(x, chunk_rows=256))


class TestPayloadBytes:
    def test_per_shard_fraction(self):
        """The facade must book this process's device fraction of the
        operand (the 2-process half is regression-tested in the
        pseudo-cluster suite; here the fraction is stubbed)."""
        from oap_mllib_tpu.parallel.collective import _payload_bytes

        class Dev:
            def __init__(self, pidx):
                self.process_index = pidx

        class Sharding:
            device_set = {Dev(0), Dev(0), Dev(1), Dev(1)}

        class Arr:
            nbytes = 1024
            sharding = Sharding()

        import jax

        local = sum(1 for d in Sharding.device_set
                    if d.process_index == jax.process_index())
        assert local == 2  # this process "owns" 2 of the 4 stub devices
        assert _payload_bytes(Arr()) == 1024 * local // 4

    def test_host_array_books_full_size(self):
        from oap_mllib_tpu.parallel.collective import _payload_bytes

        assert _payload_bytes(np.ones((8, 8), np.float32)) == 256

    def test_single_process_mesh_books_full_size(self):
        """All 8 virtual devices are local to this one process, so the
        booked bytes equal the global size — the single-process books
        are unchanged by the per-shard fix."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from oap_mllib_tpu.parallel.collective import _payload_bytes
        from oap_mllib_tpu.parallel.mesh import get_mesh

        mesh = get_mesh()
        x = jax.device_put(
            jnp.ones((16, 4), jnp.float32),
            NamedSharding(mesh, P("data", None)),
        )
        assert _payload_bytes(x) == x.nbytes


class TestLocksSanitizer:
    """The runtime half of the oaplint concurrency pass (ISSUE 14):
    tracked-lock order witnessing, hold-time accounting, and the
    off-path contract (utils/locktrace.py)."""

    def test_off_is_a_plain_lock_recording_nothing(self):
        from oap_mllib_tpu.utils import locktrace

        a = locktrace.TrackedLock("t.off.a")
        b = locktrace.TrackedLock("t.off.b")
        with a:
            with b:
                pass
        with b:  # the inversion that would raise when armed
            with a:
                pass
        assert locktrace.order_edges() == {}

    def test_live_inversion_raises_naming_both_stacks(self):
        from oap_mllib_tpu.utils import locktrace

        set_config(sanitizers="locks")
        a = locktrace.TrackedLock("t.inv.a")
        b = locktrace.TrackedLock("t.inv.b")

        def first_order():
            with a:
                with b:
                    pass

        first_order()
        assert ("t.inv.a", "t.inv.b") in locktrace.order_edges()
        with pytest.raises(san.LockOrderError) as ei:
            with b:
                with a:
                    pass
        msg = str(ei.value)
        assert "t.inv.a" in msg and "t.inv.b" in msg
        # both witness stacks ride the diagnostic: the recorded
        # first-ordering frames (inside first_order) and this one's
        assert "first_order" in msg
        assert "This acquisition" in msg and "Recorded witness" in msg

    def test_two_thread_inversion_raises_in_the_second_thread(self):
        import threading

        from oap_mllib_tpu.utils import locktrace

        set_config(sanitizers="locks")
        a = locktrace.TrackedLock("t.thr.a")
        b = locktrace.TrackedLock("t.thr.b")
        box = {}

        def leg1():
            with a:
                with b:
                    pass

        def leg2():
            try:
                with b:
                    with a:
                        pass
            except san.LockOrderError as e:
                box["err"] = e

        t1 = threading.Thread(target=leg1)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=leg2)
        t2.start()
        t2.join()
        assert isinstance(box.get("err"), san.LockOrderError)

    def test_reentrant_rlock_neither_edges_nor_restarts_clock(self):
        import threading

        from oap_mllib_tpu.utils import locktrace

        set_config(sanitizers="locks")
        r = locktrace.TrackedLock("t.re.r", threading.RLock())
        with r:
            with r:
                pass
        assert locktrace.order_edges() == {}

    def test_hold_time_histogram_populated(self):
        import time

        from oap_mllib_tpu.telemetry import metrics as _tm
        from oap_mllib_tpu.utils import locktrace

        set_config(sanitizers="locks")
        lk = locktrace.TrackedLock("t.hold")
        base = _tm.family_total("oap_lock_hold_seconds")
        with lk:
            time.sleep(0.001)
        assert _tm.family_total("oap_lock_hold_seconds") > base
        assert locktrace.hold_quantile(0.99) > 0.0

    def test_hold_past_collective_deadline_flags_never_kills(self):
        import time

        from oap_mllib_tpu.telemetry import metrics as _tm
        from oap_mllib_tpu.utils import locktrace

        set_config(sanitizers="locks", collective_timeout=0.001)
        lk = locktrace.TrackedLock("t.flag")
        before = _tm.family_total("oap_lock_hold_flags_total")
        with lk:  # exceeds the deadline; must flag, not raise
            time.sleep(0.01)
        assert _tm.family_total("oap_lock_hold_flags_total") == before + 1

    def test_live_seams_are_tracked(self):
        """The registered seams of ISSUE 14 exist by name: serving
        registry, fleet state/server, telemetry sink, sanitizer seq."""
        import oap_mllib_tpu.serving.registry  # noqa: F401 — registers
        import oap_mllib_tpu.telemetry.export  # noqa: F401
        import oap_mllib_tpu.telemetry.fleet  # noqa: F401
        from oap_mllib_tpu.utils import locktrace

        names = set(locktrace.tracked_names())
        assert {"serving.registry", "fleet.state", "fleet.server",
                "telemetry.sink", "sanitizers.seq"} <= names

    def test_serving_request_path_runs_clean_armed(self, rng):
        """A served-model storm under the locks sanitizer: the live
        seams must be inversion-free (the runtime proof next to the
        analyzer's clean R19 pass)."""
        from oap_mllib_tpu import serving
        from oap_mllib_tpu.models.kmeans import KMeans

        x = rng.normal(size=(256, 8)).astype(np.float32)
        model = KMeans(k=3, seed=1, init_mode="random", max_iter=2).fit(x)
        set_config(sanitizers="locks")
        handle = serving.serve(model)
        for rows in (3, 17, 64):
            handle.predict(x[:rows])
        serving.registry.clear()

    def test_locks_payload_lands_in_summary(self):
        set_config(sanitizers="locks")
        summary = {}
        san.finalize_fit_sanitizers(summary)
        payload = summary["sanitizers"]
        assert payload["enabled"] == ["locks"]
        assert set(payload["locks"]) == {"tracked", "order_edges",
                                         "hold_p99_s"}


class TestOverheadAndSummary:
    def test_sanitizers_off_is_summary_free(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        x = rng.normal(size=(256, 6)).astype(np.float32)
        m = KMeans(k=3, seed=1, init_mode="random", max_iter=2).fit(x)
        assert not hasattr(m.summary, "sanitizers")

    def test_enabled_set_lands_in_summary(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(sanitizers="retrace,transfer")
        x = rng.normal(size=(256, 6)).astype(np.float32)
        m = KMeans(k=3, seed=1, init_mode="random", max_iter=2).fit(x)
        assert m.summary.sanitizers["enabled"] == ["retrace", "transfer"]
