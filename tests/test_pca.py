"""PCA parity + behavior tests.

Modeled on the reference's IntelPCASuite (IntelPCASuite.scala:39-104):
oracle = independent covariance eigendecomposition, absTol 1e-5-ish,
principal components compared BY ABSOLUTE VALUE (eigenvector sign flip,
:80-82), only where explained variance is non-negligible (:84), plus
read/write round-trip (:90-104).
"""

import numpy as np
import pytest

from oap_mllib_tpu import PCA, PCAModel
from oap_mllib_tpu.config import set_config


def _data(rng, n=500, d=12):
    """Correlated gaussian data with a clear spectrum."""
    basis = rng.normal(size=(d, d))
    scales = np.linspace(3.0, 0.1, d)
    return rng.normal(size=(n, d)) @ (basis * scales[None, :])


def _oracle(x, k):
    """Independent oracle: covariance eigh (Spark RowMatrix semantics)."""
    xc = x - x.mean(0)
    cov = xc.T @ xc / (len(x) - 1)
    vals, vecs = np.linalg.eigh(cov)
    vals, vecs = vals[::-1], vecs[:, ::-1]
    return vecs[:, :k], vals[:k] / vals.sum()


class TestParity:
    def test_components_match_oracle_sign_insensitive(self, rng):
        x = _data(rng)
        k = 5
        model = PCA(k=k).fit(x)
        assert model.summary["accelerated"]
        pc_ref, ev_ref = _oracle(x, k)
        # sign-insensitive compare where explained variance is significant
        # (reference IntelPCASuite.scala:80-86)
        for j in range(k):
            if ev_ref[j] > 1e-5:
                np.testing.assert_allclose(
                    np.abs(model.components_[:, j]), np.abs(pc_ref[:, j]),
                    atol=1e-3,
                )
        np.testing.assert_allclose(model.explained_variance_, ev_ref, atol=1e-4)

    def test_accelerated_vs_fallback(self, rng):
        x = _data(rng)
        m_acc = PCA(k=4).fit(x)
        set_config(device="cpu")
        m_fb = PCA(k=4).fit(x)
        assert not m_fb.summary["accelerated"]
        np.testing.assert_allclose(
            np.abs(m_acc.components_), np.abs(m_fb.components_), atol=1e-3
        )
        np.testing.assert_allclose(
            m_acc.explained_variance_, m_fb.explained_variance_, atol=1e-4
        )

    def test_explained_variance_sums_below_one(self, rng):
        x = _data(rng)
        model = PCA(k=3).fit(x)
        assert 0 < model.explained_variance_.sum() <= 1.0 + 1e-6
        # descending
        assert np.all(np.diff(model.explained_variance_) <= 1e-9)


class TestPrecisionTiers:
    """Tier threading through the estimator (every tier runs the centered
    two-pass Gram; on CPU all tiers are full f32, so these check the
    plumbing + oracle parity; the per-tier bf16 error bounds are pinned
    on tests_tpu)."""

    def test_high_tier_matches_highest(self, rng):
        x = _data(rng, n=400, d=12) + 25.0  # large means: worst case
        m_hi = PCA(k=4).fit(x)
        set_config(matmul_precision="high")
        m_fast = PCA(k=4).fit(x)
        np.testing.assert_allclose(
            m_fast.explained_variance_, m_hi.explained_variance_, atol=1e-5
        )
        np.testing.assert_allclose(
            np.abs(m_fast.components_), np.abs(m_hi.components_), atol=1e-4
        )

    def test_high_tier_model_sharded(self, rng):
        x = _data(rng, n=256, d=8) + 10.0
        set_config(matmul_precision="high", model_parallel=2)
        m = PCA(k=3).fit(x)
        assert m.summary["mesh_shape"]["model"] == 2
        pc_ref, ev_ref = _oracle(x, 3)
        np.testing.assert_allclose(m.explained_variance_, ev_ref, atol=1e-4)
        np.testing.assert_allclose(
            np.abs(m.components_), np.abs(pc_ref), atol=1e-3
        )

    def test_invalid_tier_raises(self, rng):
        x = _data(rng, n=64, d=6)
        set_config(matmul_precision="typo")
        with pytest.raises(ValueError, match="matmul_precision"):
            PCA(k=2).fit(x)

    def test_large_mean_cancellation_regression(self, rng):
        """mean >> stddev data at f32: the retired raw-moment form lost
        ~4e-3 relative through the gram ~ n*mu*mu^T cancellation; the
        centered form must stay on the oracle."""
        x = rng.normal(size=(2000, 8)) + 100.0
        model = PCA(k=3).fit(x.astype(np.float32))
        pc_ref, ev_ref = _oracle(x, 3)
        np.testing.assert_allclose(model.explained_variance_, ev_ref, atol=1e-4)
        np.testing.assert_allclose(
            np.abs(model.components_), np.abs(pc_ref), atol=1e-3
        )


class TestModelParallel:
    """Mesh-sharded linalg: the Gram/covariance rows sharded over the
    MODEL axis of a 2-D (data=4, model=2) mesh (survey §5's "mesh-sharded
    linalg" scope — a real estimator path, not just the driver dryrun)."""

    def test_2d_mesh_matches_oracle(self, rng):
        x = _data(rng, n=400, d=12)
        k = 5
        set_config(model_parallel=2)
        model = PCA(k=k).fit(x)
        assert model.summary["accelerated"]
        # the fit really ran on a (4, 2) mesh
        assert model.summary["mesh_shape"] == {"data": 4, "model": 2}
        pc_ref, ev_ref = _oracle(x, k)
        for j in range(k):
            if ev_ref[j] > 1e-5:
                np.testing.assert_allclose(
                    np.abs(model.components_[:, j]), np.abs(pc_ref[:, j]),
                    atol=1e-3,
                )
        np.testing.assert_allclose(model.explained_variance_, ev_ref, atol=1e-4)

    def test_2d_mesh_feature_padding(self, rng):
        """d=11 does not divide model=2: zero-padded feature columns must
        not perturb the components or the variance ratios."""
        x = _data(rng, n=300, d=11)
        set_config(model_parallel=2)
        model = PCA(k=3).fit(x)
        assert model.components_.shape == (11, 3)
        pc_ref, ev_ref = _oracle(x, 3)
        np.testing.assert_allclose(
            np.abs(model.components_), np.abs(pc_ref), atol=1e-3
        )
        np.testing.assert_allclose(model.explained_variance_, ev_ref, atol=1e-4)

    def test_2d_mesh_rank_deficient_padding_tie(self, rng):
        """Rank-deficient data + padded columns: the genuine null-space
        eigenvector must win the tie at eigenvalue 0, never a padded basis
        vector (which would slice to a zero component column)."""
        # d=3 padded to 4 under model=2; data spans only 2 directions
        base = rng.normal(size=(200, 2))
        x = np.concatenate([base, (base[:, :1] + base[:, 1:])], axis=1)  # col3 = col1+col2
        set_config(model_parallel=2)
        model = PCA(k=3).fit(x)
        norms = np.linalg.norm(model.components_, axis=0)
        np.testing.assert_allclose(norms, 1.0, atol=1e-4)  # no zero column
        # the k=3 component is the true null direction (1,1,-1)/sqrt(3)
        np.testing.assert_allclose(
            np.abs(model.components_[:, 2]), np.abs(np.array([1, 1, -1]) / np.sqrt(3)),
            atol=1e-3,
        )

    def test_2d_matches_1d(self, rng):
        x = _data(rng, n=256, d=8)
        m1 = PCA(k=4).fit(x)
        set_config(model_parallel=2)
        m2 = PCA(k=4).fit(x)
        assert m2.summary["mesh_shape"]["model"] == 2
        assert m1.summary["mesh_shape"]["model"] == 1
        np.testing.assert_allclose(
            np.abs(m1.components_), np.abs(m2.components_), atol=1e-4
        )


class TestBehavior:
    def test_shapes(self, rng):
        x = _data(rng, n=100, d=7)
        model = PCA(k=3).fit(x)
        assert model.components_.shape == (7, 3)
        assert model.explained_variance_.shape == (3,)
        assert model.transform(x).shape == (100, 3)

    def test_transform_no_centering_spark_parity(self, rng):
        """Spark's PCAModel.transform projects WITHOUT subtracting the mean."""
        x = _data(rng, n=50, d=5) + 10.0  # big offset
        model = PCA(k=2).fit(x)
        expected = x.astype(np.float32) @ model.components_
        np.testing.assert_allclose(model.transform(x), expected, atol=1e-3)

    def test_k_validation(self, rng):
        with pytest.raises(ValueError):
            PCA(k=0)
        with pytest.raises(ValueError):
            PCA(k=10).fit(np.zeros((5, 3)))

    def test_uneven_rows(self, rng):
        for n in (9, 17, 101):
            x = _data(rng, n=n, d=6)
            model = PCA(k=2).fit(x)
            pc_ref, ev_ref = _oracle(x, 2)
            np.testing.assert_allclose(
                np.abs(model.components_), np.abs(pc_ref), atol=1e-3
            )


class TestRandomizedSolver:
    """pca_solver="randomized": top-k subspace iteration vs full eigh.
    Vector parity is claimed ONLY on decaying spectra (the ops docstring
    contract); near-flat spectra pin eigenvalue agreement alone."""

    def _decaying(self, rng, n=2000, d=64):
        # strongly decaying spectrum: well-separated top eigenpairs
        scales = 2.0 ** -np.arange(d)
        basis = np.linalg.qr(rng.normal(size=(d, d)))[0]
        x = rng.normal(size=(n, d)) * scales[None, :] * 10
        return (x @ basis.T).astype(np.float32)

    def test_matches_eigh_on_decaying_spectrum(self, rng):
        from oap_mllib_tpu.config import set_config

        x = self._decaying(rng)
        m_eigh = PCA(k=5).fit(x)
        set_config(pca_solver="randomized")
        m_rand = PCA(k=5).fit(x)
        np.testing.assert_allclose(
            m_rand.explained_variance_, m_eigh.explained_variance_,
            rtol=1e-4, atol=1e-6,
        )
        # sign-insensitive vector match (IntelPCASuite pattern)
        dots = np.abs(
            np.einsum("dk,dk->k", m_rand.components_, m_eigh.components_)
        )
        assert np.all(dots > 1.0 - 1e-4), dots

    def test_flat_spectrum_eigenvalues_only(self, rng):
        """Isotropic noise: the top-k subspace is ill-defined, so only
        the eigenVALUES are pinned (to the flat level)."""
        from oap_mllib_tpu.config import set_config

        x = rng.normal(size=(5000, 32)).astype(np.float32)
        m_eigh = PCA(k=4).fit(x)
        set_config(pca_solver="randomized")
        m_rand = PCA(k=4).fit(x)
        np.testing.assert_allclose(
            m_rand.explained_variance_, m_eigh.explained_variance_,
            rtol=0.05,
        )

    def test_streamed_randomized(self, rng):
        from oap_mllib_tpu.config import set_config
        from oap_mllib_tpu.data.stream import ChunkSource

        x = self._decaying(rng, n=1500, d=32)
        set_config(pca_solver="randomized")
        m_s = PCA(k=3).fit(ChunkSource.from_array(x, chunk_rows=256))
        m_m = PCA(k=3).fit(x)
        np.testing.assert_allclose(
            np.abs(m_s.components_), np.abs(m_m.components_), atol=1e-4
        )

    def test_model_sharded_randomized(self, rng):
        """model_parallel=2 pads feature dims; the randomized path must
        slice the padding off (NOT -1-demote it: subspace iteration
        ranks by |eigenvalue|)."""
        from oap_mllib_tpu.config import set_config

        x = self._decaying(rng, n=1000, d=31)  # 31 % 2 != 0 -> padded
        m_ref = PCA(k=3).fit(x)
        set_config(pca_solver="randomized", model_parallel=2)
        m = PCA(k=3).fit(x)
        assert m.components_.shape == (31, 3)
        dots = np.abs(np.einsum("dk,dk->k", m.components_, m_ref.components_))
        assert np.all(dots > 1.0 - 1e-3), dots

    def test_k_larger_than_probe_cap(self, rng):
        """k + oversample > d clamps the probe to d and still works."""
        from oap_mllib_tpu.config import set_config

        x = self._decaying(rng, n=500, d=10)
        set_config(pca_solver="randomized")
        m = PCA(k=9).fit(x)
        assert m.components_.shape == (10, 9)
        assert np.isfinite(m.components_).all()

    def test_invalid_solver_raises(self, rng):
        from oap_mllib_tpu.config import set_config

        set_config(pca_solver="randomised")
        with pytest.raises(ValueError, match="pca_solver"):
            PCA(k=2).fit(_data(rng, n=50, d=5))

    def test_tuning_knobs_flow_through(self, rng):
        """pca_rand_oversample/iters reach the solver: cranking them on a
        weakly-gapped spectrum tightens the eigenvalues toward eigh."""
        from oap_mllib_tpu.config import set_config

        x = rng.normal(size=(3000, 48)).astype(np.float32)
        ref = PCA(k=4).fit(x).explained_variance_
        set_config(pca_solver="randomized", pca_rand_oversample=2,
                   pca_rand_iters=1)
        loose = PCA(k=4).fit(x).explained_variance_
        set_config(pca_rand_oversample=44, pca_rand_iters=24)
        tight = PCA(k=4).fit(x).explained_variance_
        assert np.abs(tight - ref).max() < np.abs(loose - ref).max()
        np.testing.assert_allclose(tight, ref, rtol=5e-3)
        set_config(pca_rand_iters=0)
        with pytest.raises(ValueError, match="pca_rand"):
            PCA(k=4).fit(x)


class TestPersistence:
    def test_save_load_roundtrip(self, tmp_path, rng):
        x = _data(rng)
        model = PCA(k=3).fit(x)
        p = str(tmp_path / "pca_model")
        model.save(p)
        loaded = PCAModel.load(p)
        np.testing.assert_array_equal(loaded.components_, model.components_)
        np.testing.assert_array_equal(
            loaded.explained_variance_, model.explained_variance_
        )
