"""Live-world recovery pseudo-cluster worker (ISSUE 10).

One rank of a real ``jax.distributed`` world driving the recovery plane
(utils/recovery.py).  Modes (env ``RECOVERY_WORKER_MODE``):

- ``hang`` — rank 1 SIGKILLs itself mid-read of Lloyd pass 2 (a
  preemption, no cleanup); rank 0 finishes its local pass and blocks in
  the cross-process reduction.  With ``collective_timeout`` armed, rank
  0 must raise :class:`CollectiveTimeoutError` within the deadline —
  NOT hang until the parent's 120 s watchdog — print
  ``TIMEOUT_CAUGHT`` and exit 0 on its own, leaving its crash record in
  the sideband.
- ``abort`` — rank 1 writes a crash record for a fatal fault that never
  reaches a collective, then exits; rank 0, blocked inside its first
  pass reduction, must see the poison and raise
  :class:`PeerAbortError` promptly (print ``PEER_ABORT_CAUGHT``).

Invoked as:  python pseudo_cluster_worker_recovery.py RANK NPROC COORD LOCAL_DEV
(the standard worker argv — the shared _launch_world plumbing spawns it).
"""

import os
import sys

rank, nproc = int(sys.argv[1]), int(sys.argv[2])
coord, local_dev = sys.argv[3], int(sys.argv[4])
mode = os.environ["RECOVERY_WORKER_MODE"]
crash_dir = os.environ["RECOVERY_CRASH_DIR"]

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={local_dev}"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
if hasattr(jax.config, "jax_num_cpu_devices"):
    jax.config.update("jax_num_cpu_devices", local_dev)

import numpy as np

from oap_mllib_tpu.parallel import bootstrap

ran = bootstrap.initialize_distributed(coord, nproc, rank)
assert ran, "initialize_distributed returned False"

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.models.kmeans import KMeans
from oap_mllib_tpu.utils import recovery

# the deadline is the mechanism under test: well under the parent's
# 120 s watchdog, well over a healthy pass
set_config(collective_timeout=10.0, crash_dir=crash_dir)

rng = np.random.default_rng(321)
x = rng.normal(size=(3000, 8)).astype(np.float32)
shard = x[rank * 1500: (rank + 1) * 1500]

if mode == "abort" and rank == 1:
    # a fatal fault that never reaches a common reduction: the sideband
    # is the only way peers can learn about it promptly
    recovery.write_crash_record(
        "drill.fault", "unclassified", "injected fatal fault (abort drill)"
    )
    print("ABORT_RECORDED rank=1", flush=True)
    os._exit(3)

walks = {"n": 0}


def gen():
    walks["n"] += 1
    # walk 1 = the random-init reservoir pass; the victim dies mid-read
    # of Lloyd pass 2 (walk 3) — rank 0 is left inside the pass
    # reduction for the deadline plane to convert into a diagnosis
    if mode == "hang" and rank == 1 and walks["n"] == 3:
        import signal

        os.kill(os.getpid(), signal.SIGKILL)
    for lo in range(0, shard.shape[0], 500):
        yield shard[lo: lo + 500]


src = ChunkSource(gen, shard.shape[1], 500, n_rows=shard.shape[0])
try:
    m = KMeans(k=4, seed=7, init_mode="random", max_iter=6, tol=0.0).fit(src)
except recovery.CollectiveTimeoutError as e:
    print(f"TIMEOUT_CAUGHT rank={rank} op={e.op} "
          f"elapsed={e.elapsed_s:.1f}", flush=True)
    os._exit(0)  # crash record written; skip jax shutdown (peer is gone)
except recovery.PeerAbortError as e:
    peer = e.record.get("rank")
    print(f"PEER_ABORT_CAUGHT rank={rank} peer={peer}", flush=True)
    os._exit(0)
except Exception as e:  # noqa: BLE001 — surface env-incapability markers
    print(f"WORKER_ERROR rank={rank} {type(e).__name__}: {e}", flush=True)
    os._exit(4)

print(f"RESULT_UNEXPECTED rank={rank} cost={m.summary.training_cost}",
      flush=True)
os._exit(5)  # both drill modes must end in a recovery-plane exit
