"""Sanitizer-plane worker: one rank of a real 2-process world, driving
the runtime sanitizers (utils/sanitizers.py) where they matter — across
an actual process boundary.

Modes (env ``SANITIZER_WORKER_MODE``, set by the parent test):

- ``diverge`` — rank 0 dispatches ``allreduce_sum`` while rank 1
  dispatches ``allgather_rows`` (the classic rank-divergent-collective
  shape that HANGS a world until the distributed timeout).  With the
  ``collective`` sanitizer armed, BOTH ranks must raise
  ``CollectiveDivergenceError`` promptly, each naming its own op and the
  first differing rank's op.  Exit 0 iff the divergence was caught.
- ``probe`` — (a) facade byte accounting: one ``allreduce_sum`` over a
  row-sharded table must book THIS PROCESS's shard bytes (half the
  global array in a 2-rank world), not the unsharded size (the ISSUE 7
  satellite regression); (b) a streamed K-Means fit with every
  sanitizer armed must succeed, with the collective fingerprint
  world-checked and identical across ranks.

Invoked as:  python pseudo_cluster_worker_sanitizer.py RANK NPROC COORD LOCAL_DEVICES
(the standard worker argv — the shared _launch_world plumbing spawns it).
"""

import json
import sys

rank, nproc = int(sys.argv[1]), int(sys.argv[2])
coord, local_dev = sys.argv[3], int(sys.argv[4])

import os

mode = os.environ.get("SANITIZER_WORKER_MODE", "probe")

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={local_dev}"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
if hasattr(jax.config, "jax_num_cpu_devices"):
    jax.config.update("jax_num_cpu_devices", local_dev)

import numpy as np

from oap_mllib_tpu.parallel import bootstrap

assert bootstrap.initialize_distributed(coord, nproc, rank)

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.data.table import DenseTable
from oap_mllib_tpu.models.kmeans import KMeans
from oap_mllib_tpu.parallel import collective
from oap_mllib_tpu.parallel.mesh import get_mesh
from oap_mllib_tpu.telemetry import metrics as tm
from oap_mllib_tpu.utils.sanitizers import CollectiveDivergenceError

rng = np.random.default_rng(123)
x = rng.normal(size=(4000, 12)).astype(np.float32)
half = x[rank * 2000 : (rank + 1) * 2000]

mesh = get_mesh()
table = DenseTable.from_process_local(half, mesh)

if mode == "diverge":
    set_config(sanitizers="collective")
    try:
        if rank == 0:
            collective.allreduce_sum(table.data, mesh)
        else:
            collective.allgather_rows(table.data, mesh)
    except CollectiveDivergenceError as e:
        msg = str(e)
        assert "allreduce_sum" in msg and "allgather_rows" in msg, msg
        print(f"DIVERGENCE_CAUGHT rank={rank}: {msg.splitlines()[0]}",
              flush=True)
        sys.exit(0)
    print(f"NO_DIVERGENCE rank={rank} — the divergent collective was "
          "dispatched without a diagnostic", flush=True)
    sys.exit(1)

# -- mode "probe" ------------------------------------------------------------

# (a) per-shard byte accounting through the facade


def _booked_bytes() -> float:
    series = tm.snapshot().get("oap_collective_bytes_total", {})
    return float(sum(series.values()))


before = _booked_bytes()
collective.allreduce_sum(table.data, mesh)
booked = _booked_bytes() - before

# (b) streamed fit with every sanitizer armed, across the real world
set_config(sanitizers="collective,transfer,retrace")
src = ChunkSource.from_array(half, chunk_rows=512)
m = KMeans(k=5, seed=7, init_mode="random", max_iter=5).fit(src)
san = m.summary.sanitizers

print("RESULT " + json.dumps({
    "rank": rank,
    "booked_bytes": booked,
    "global_bytes": int(table.data.nbytes),
    "streamed_cost": float(m.summary.training_cost),
    "san_ops": san["collective"]["ops"],
    "san_fingerprint": san["collective"]["fingerprint"],
    "san_world_checked": san["collective"]["world_checked"],
}), flush=True)
