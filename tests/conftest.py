"""Test harness: single-host multi-rank pseudo-cluster.

The reference tests its "distributed" code as a 1-rank collective world on
local[*] (Utils.scala:119-121) plus a 2-executor pseudo-YARN cluster in CI
(survey §4).  Here the analog is stronger: an 8-device virtual CPU mesh via
``--xla_force_host_platform_device_count=8``, so every sharded program in
the suite actually executes 8-way SPMD with real XLA collectives.
"""

import os

# Force CPU even if the session env points at a real accelerator — the suite
# is the 8-rank pseudo-cluster.  Env vars alone are NOT enough: a site hook
# may pin the platform at interpreter start, so set jax config explicitly
# (wins as long as no backend has initialized yet).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
if hasattr(jax.config, "jax_num_cpu_devices"):
    # newer jax lines expose the device count as a config option; older
    # ones only honor the XLA_FLAGS env set above
    jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_config():
    """Fresh global config per test."""
    import oap_mllib_tpu.config as cfgmod

    with cfgmod._lock:
        cfgmod._config = None
    yield
    with cfgmod._lock:
        cfgmod._config = None


@pytest.fixture
def rng():
    return np.random.default_rng(42)
