"""Serving-plane replica-eviction pseudo-cluster worker (ISSUE 13).

One replica of a REAL ``jax.distributed`` serving fleet: both ranks pin
the same fitted K-Means model (replicated weights), answer identical
request legs, and heartbeat between legs over the deadline-watchdogged
host collective plane (serving/ha.py).  Modes (env
``SERVING_WORKER_MODE``):

- ``evict`` — rank 1 SIGKILLs itself before the heartbeat of leg 3 (a
  preempted replica); rank 0's next heartbeat must convert into a
  ``CollectiveTimeoutError`` which the :class:`ReplicaGuard` absorbs:
  the survivor EVICTS the fleet view, keeps answering the remaining
  legs in local-only mode, and its answers are bit-identical before
  and after the eviction (printed as per-leg digests the parent
  cross-checks).  Exit 0 with ``EVICTED`` + ``SERVE_OK`` markers.
- ``relaunched`` — the supervisor's replacement replica: a 1-process
  world (nproc=1) that serves the same request legs and prints the
  same digests, so the parent can assert the relaunch answers exactly
  what the survivor does.

Invoked as:  python pseudo_cluster_worker_serving.py RANK NPROC COORD LOCAL_DEV
(the standard worker argv — the shared _launch_world plumbing spawns it).
"""

import hashlib
import os
import sys

rank, nproc = int(sys.argv[1]), int(sys.argv[2])
coord, local_dev = sys.argv[3], int(sys.argv[4])
mode = os.environ["SERVING_WORKER_MODE"]
crash_dir = os.environ["SERVING_CRASH_DIR"]

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={local_dev}"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
if hasattr(jax.config, "jax_num_cpu_devices"):
    jax.config.update("jax_num_cpu_devices", local_dev)

import numpy as np

if nproc > 1:
    from oap_mllib_tpu.parallel import bootstrap

    ran = bootstrap.initialize_distributed(coord, nproc, rank)
    assert ran, "initialize_distributed returned False"

from oap_mllib_tpu import serving
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.models.kmeans import KMeans

# the deadline is the mechanism under test: well under the parent's
# 120 s watchdog, well over a healthy heartbeat
set_config(collective_timeout=10.0, crash_dir=crash_dir)

# every replica fits the same model from the same data (replicated
# weights — the serving fleet contract) and serves the same requests
rng = np.random.default_rng(77)
x = rng.normal(size=(600, 8)).astype(np.float32)
model = KMeans(k=4, seed=5, init_mode="random", max_iter=4).fit(x)
handle = serving.serve(model)
handle.warmup(128)

requests = [
    rng.normal(size=(int(s), 8)).astype(np.float32)
    for s in rng.integers(5, 128, size=6)
]

guard = serving.ReplicaGuard()
digests = []
announced = False
for leg, batch in enumerate(requests):
    if mode == "evict" and rank == 1 and nproc > 1 and leg == 3:
        import signal

        os.kill(os.getpid(), signal.SIGKILL)  # a preempted replica
    with guard.leg():
        ids = handle.predict(batch)
        digests.append(hashlib.sha256(ids.tobytes()).hexdigest()[:16])
        print(f"ANSWER rank={rank} leg={leg} digest={digests[-1]}",
              flush=True)
        if not guard.local_only and nproc > 1:
            view = serving.heartbeat(requests=handle.requests)
            if leg == 0:
                print(f"FLEET rank={rank} world={view['world']}",
                      flush=True)
    if guard.local_only and not announced:
        # first leg whose heartbeat the guard absorbed: announce the
        # eviction once — the survivor keeps answering locally
        announced = True
        err = type(guard.last_error).__name__
        print(f"EVICTED rank={rank} leg={leg} err={err}", flush=True)

print(f"SERVE_OK rank={rank} legs={len(digests)} "
      f"local_only={guard.local_only}", flush=True)
os._exit(0)
