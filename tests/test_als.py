"""ALS parity + behavior tests.

The reference's own ALS suite was disabled (survey §4 — IntelALSSuite
commented out of test.sh), so ALS parity is built fresh here, per the
survey takeaway: independent NumPy oracle, identical factor init for exact
comparison, plus regression-style implicit-feedback checks modeled on
Spark's ALSSuite implicit test (preference/confidence reconstruction).
"""

import numpy as np
import pytest

from oap_mllib_tpu import ALS, ALSModel
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.fallback.als_np import init_factors


def _ratings(rng, n_users=40, n_items=30, density=0.3):
    mask = rng.random((n_users, n_items)) < density
    u, i = np.nonzero(mask)
    r = rng.integers(1, 6, size=len(u)).astype(np.float32)
    return u, i, r, n_users, n_items


def _oracle_half(dst_n, dst_idx, src_idx, rating, src, reg, alpha, implicit):
    """Independent per-row normal-equation solve (test-local oracle).

    Spark ALS-WR convention (reference spark-3.1.1/ml/recommendation/
    ALS.scala:1781-1795): lambda is scaled by the per-row rating count
    (r>0 count for implicit, all ratings for explicit); implicit uses
    c1 = alpha*|r| in A for every rating and adds b only when r > 0.
    Rows with no reg-counted ratings get zero factors.
    """
    rank = src.shape[1]
    out = np.zeros((dst_n, rank))
    gram = src.T @ src
    for d in range(dst_n):
        sel = dst_idx == d
        ys = src[src_idx[sel]]
        rs = rating[sel].astype(np.float64)
        if implicit:
            c1 = alpha * np.abs(rs)
            pos = rs > 0
            n_reg = float(pos.sum())
            if n_reg == 0.0:
                continue
            a = gram + ys.T @ (ys * c1[:, None]) + reg * n_reg * np.eye(rank)
            b = ((1.0 + c1)[:, None] * ys)[pos].sum(0)
        else:
            n_reg = float(len(rs))
            if n_reg == 0.0:
                continue
            a = ys.T @ ys + reg * n_reg * np.eye(rank)
            b = (rs[:, None] * ys).sum(0)
        out[d] = np.linalg.solve(a, b)
    return out


def _oracle_als(u, i, r, nu, ni, rank, iters, reg, alpha, implicit, x0, y0):
    x, y = x0.astype(np.float64), y0.astype(np.float64)
    for _ in range(iters):
        x = _oracle_half(nu, u, i, r, y, reg, alpha, implicit)
        y = _oracle_half(ni, i, u, r, x, reg, alpha, implicit)
    return x, y


class TestParity:
    @pytest.mark.parametrize("implicit", [True, False])
    def test_factors_match_oracle_fixed_init(self, rng, implicit):
        u, i, r, nu, ni = _ratings(rng)
        rank, iters, reg, alpha = 6, 3, 0.1, 0.8
        x0 = init_factors(nu, rank, 1)
        y0 = init_factors(ni, rank, 2)
        model = ALS(
            rank=rank, max_iter=iters, reg_param=reg, alpha=alpha,
            implicit_prefs=implicit,
        ).fit(u, i, r, init=(x0, y0))
        assert model.summary["accelerated"]
        ox, oy = _oracle_als(u, i, r, nu, ni, rank, iters, reg, alpha, implicit, x0, y0)
        np.testing.assert_allclose(model.user_factors_, ox, atol=2e-3, rtol=2e-3)
        np.testing.assert_allclose(model.item_factors_, oy, atol=2e-3, rtol=2e-3)

    @pytest.mark.parametrize("implicit", [True, False])
    def test_accelerated_vs_fallback(self, rng, implicit):
        u, i, r, nu, ni = _ratings(rng)
        x0 = init_factors(nu, 4, 1)
        y0 = init_factors(ni, 4, 2)
        kw = dict(rank=4, max_iter=3, reg_param=0.2, alpha=1.0, implicit_prefs=implicit)
        m_acc = ALS(**kw).fit(u, i, r, init=(x0, y0))
        set_config(device="cpu")
        m_fb = ALS(**kw).fit(u, i, r, init=(x0, y0))
        assert not m_fb.summary["accelerated"]
        np.testing.assert_allclose(m_acc.user_factors_, m_fb.user_factors_, atol=2e-3, rtol=2e-3)
        np.testing.assert_allclose(m_acc.item_factors_, m_fb.item_factors_, atol=2e-3, rtol=2e-3)

    def test_explicit_rmse_decreases(self, rng):
        """Low-rank synthetic ratings should be fit well (rank-recovery
        regression, modeled on Spark ALSSuite exact-rank-1 tests)."""
        nu, ni, rank = 50, 40, 3
        xt = rng.normal(size=(nu, rank))
        yt = rng.normal(size=(ni, rank))
        full = xt @ yt.T
        mask = rng.random((nu, ni)) < 0.5
        u, i = np.nonzero(mask)
        r = full[u, i].astype(np.float32)
        model = ALS(rank=rank, max_iter=10, reg_param=0.01).fit(u, i, r)
        pred = model.predict(u, i)
        rmse = np.sqrt(np.mean((pred - r) ** 2))
        assert rmse < 0.1 * np.std(r)

    def test_implicit_nonpositive_ratings_match_oracle(self, rng):
        """Zero/negative ratings exercise the Spark nonpositive-rating
        semantics: c1 = alpha*|r| keeps A PSD, b/n_reg count only r > 0
        (reference ALS.scala:1781-1795)."""
        u, i, r, nu, ni = _ratings(rng, n_users=30, n_items=20)
        signs = rng.choice([-1.0, 0.0, 1.0], size=len(r), p=[0.2, 0.1, 0.7])
        r = (r * signs).astype(np.float32)
        rank, iters, reg, alpha = 5, 3, 0.1, 0.8
        x0 = init_factors(nu, rank, 1)
        y0 = init_factors(ni, rank, 2)
        model = ALS(
            rank=rank, max_iter=iters, reg_param=reg, alpha=alpha,
            implicit_prefs=True,
        ).fit(u, i, r, n_users=nu, n_items=ni, init=(x0, y0))
        assert model.summary["accelerated"]
        ox, oy = _oracle_als(u, i, r, nu, ni, rank, iters, reg, alpha, True, x0, y0)
        np.testing.assert_allclose(model.user_factors_, ox, atol=2e-3, rtol=2e-3)
        np.testing.assert_allclose(model.item_factors_, oy, atol=2e-3, rtol=2e-3)

    def test_implicit_preference_ordering(self, rng):
        """Implicit model scores observed items above unobserved ones
        (the implicit-feedback behavioral contract)."""
        u, i, r, nu, ni = _ratings(rng, density=0.2)
        model = ALS(rank=8, max_iter=8, reg_param=0.05, alpha=2.0,
                    implicit_prefs=True).fit(u, i, r, n_users=nu, n_items=ni)
        scores = model.user_factors_ @ model.item_factors_.T
        observed = np.zeros((nu, ni), dtype=bool)
        observed[u, i] = True
        mean_obs = scores[observed].mean()
        mean_unobs = scores[~observed].mean()
        assert mean_obs > mean_unobs + 0.1


class TestBehavior:
    def test_shapes_and_rank(self, rng):
        u, i, r, nu, ni = _ratings(rng)
        model = ALS(rank=5, max_iter=2).fit(u, i, r)
        assert model.user_factors_.shape == (nu if u.max() == nu - 1 else u.max() + 1, 5)
        assert model.item_factors_.shape[1] == 5
        assert model.rank == 5

    def test_predict_pairs(self, rng):
        u, i, r, nu, ni = _ratings(rng)
        model = ALS(rank=4, max_iter=2).fit(u, i, r)
        pred = model.predict(u[:10], i[:10])
        expected = np.sum(model.user_factors_[u[:10]] * model.item_factors_[i[:10]], axis=1)
        np.testing.assert_allclose(pred, expected, atol=1e-5)

    def test_recommend_for_all_users(self, rng):
        u, i, r, nu, ni = _ratings(rng)
        model = ALS(rank=4, max_iter=2).fit(u, i, r, n_users=nu, n_items=ni)
        recs = model.recommend_for_all_users(5)
        assert recs.shape == (nu, 5)
        assert recs.min() >= 0 and recs.max() < ni
        # row-chunked scoring (incl. a ragged tail chunk) vs the default
        # chunking, and both vs the NumPy full cross product: compare
        # SCORES, not ids — near-tie rows may order differently between
        # compiled shapes / matmul implementations
        chunked, _ = model._top_k_scores(
            model.user_factors_, model.item_factors_, 5, row_chunk=7
        )
        scores = model.user_factors_ @ model.item_factors_.T
        best = -np.sort(-scores, axis=1)[:, :5]
        np.testing.assert_allclose(
            np.take_along_axis(scores, chunked, axis=1), best, rtol=1e-5
        )
        np.testing.assert_allclose(
            np.take_along_axis(scores, recs, axis=1), best, rtol=1e-5
        )
        # empty query side: shape-(0, n) result, no crash
        empty_ids, empty_scores = model._top_k_scores(
            model.user_factors_[:0], model.item_factors_, 5
        )
        assert empty_ids.shape == empty_scores.shape == (0, 5)

    def test_recommend_with_scores(self, rng):
        """with_scores returns descending predicted preferences that
        match predict() on the same (user, item) pairs."""
        u, i, r, nu, ni = _ratings(rng)
        m = ALS(rank=4, max_iter=3, implicit_prefs=True).fit(
            u, i, r, n_users=nu, n_items=ni
        )
        ids, scores = m.recommend_for_all_users(5, with_scores=True)
        assert ids.shape == scores.shape == (nu, 5)
        assert (np.diff(scores, axis=1) <= 1e-6).all()  # descending
        uu = np.repeat(np.arange(nu), 5)
        np.testing.assert_allclose(
            scores.ravel(), m.predict(uu, ids.ravel()), atol=1e-5
        )

    def test_recommend_subsets(self, rng):
        """recommend_for_users / recommend_for_items (the reference's
        recommendForUserSubset / ItemSubset surface, ALS.scala:379-429):
        subset rows equal the corresponding all-users rows; ids out of
        range raise; scores ride along."""
        u, i, r, nu, ni = _ratings(rng)
        m = ALS(rank=4, max_iter=2, implicit_prefs=True).fit(
            u, i, r, n_users=nu, n_items=ni
        )
        subset = np.array([3, 0, 17, 3])  # unordered + duplicate
        all_ids, all_scores = m.recommend_for_all_users(
            5, with_scores=True
        )
        ids, scores = m.recommend_for_users(subset, 5, with_scores=True)
        assert ids.shape == (4, 5)
        np.testing.assert_allclose(scores, all_scores[subset], atol=1e-5)
        full = m.user_factors_[subset] @ m.item_factors_.T
        np.testing.assert_allclose(
            np.take_along_axis(full, ids, axis=1), scores, atol=1e-5
        )
        item_ids = m.recommend_for_items(np.array([1, 5]), 3)
        assert item_ids.shape == (2, 3)
        assert item_ids.max() < nu
        with pytest.raises(ValueError, match="user ids"):
            m.recommend_for_users(np.array([nu]), 3)
        with pytest.raises(ValueError, match="item ids"):
            m.recommend_for_items(np.array([-1]), 3)
        # empty subset: (0, n) result, no crash
        assert m.recommend_for_users(np.zeros((0,), np.int64), 4).shape == (0, 4)

    def test_recommend_oversized_n_clamps_like_spark(self, rng):
        """ADVICE low #4 regression: num_items/num_users beyond the
        trained table must clamp to the table size (Spark returns fewer
        rows) instead of hitting an opaque lax.top_k XLA error — on every
        recommender surface, scores riding along."""
        u, i, r, nu, ni = _ratings(rng)
        m = ALS(rank=4, max_iter=2).fit(u, i, r, n_users=nu, n_items=ni)
        ids, scores = m.recommend_for_all_users(ni + 100, with_scores=True)
        assert ids.shape == scores.shape == (nu, ni)
        exact, _ = m.recommend_for_all_users(ni, with_scores=True)
        np.testing.assert_array_equal(ids, exact)
        assert m.recommend_for_all_items(nu + 7).shape == (ni, nu)
        sub = m.recommend_for_users(np.array([0, 2]), ni + 1)
        assert sub.shape == (2, ni)
        assert m.recommend_for_items(np.array([1]), nu * 3).shape == (1, nu)
        # empty query x oversized n: clamped width, still no crash
        assert m.recommend_for_users(
            np.zeros((0,), np.int64), ni + 5
        ).shape == (0, ni)
        with pytest.raises(ValueError, match=">= 0"):
            m.recommend_for_all_users(-1)

    def test_param_validation(self):
        for bad in (dict(rank=0), dict(max_iter=-1), dict(reg_param=-0.1), dict(alpha=-1)):
            with pytest.raises(ValueError):
                ALS(**bad)
        with pytest.raises(ValueError):
            ALS().fit(np.array([0]), np.array([0, 1]), np.array([1.0]))
        with pytest.raises(ValueError):
            ALS().fit(np.array([], dtype=int), np.array([], dtype=int), np.array([]))
        with pytest.raises(ValueError):
            ALS().fit(np.array([-1]), np.array([0]), np.array([1.0]))


class TestPersistence:
    def test_save_load_roundtrip(self, tmp_path, rng):
        u, i, r, nu, ni = _ratings(rng)
        model = ALS(rank=4, max_iter=2).fit(u, i, r)
        p = str(tmp_path / "als_model")
        model.save(p)
        loaded = ALSModel.load(p)
        np.testing.assert_array_equal(loaded.user_factors_, model.user_factors_)
        np.testing.assert_array_equal(loaded.item_factors_, model.item_factors_)


class TestRegressions:
    def test_id_out_of_declared_range_raises(self, rng):
        u = np.array([0, 20]); i = np.array([0, 1]); r = np.array([1.0, 2.0], np.float32)
        with pytest.raises(ValueError):
            ALS().fit(u, i, r, n_users=10)
        with pytest.raises(ValueError):
            ALS().fit(u, i, r, n_items=1)

    def test_zero_reg_with_id_gaps_stays_finite(self):
        """reg=0 + users with no ratings must yield zero (not NaN) factors,
        matching the fallback's skip-empty-row semantics."""
        u = np.array([0, 2]); i = np.array([0, 1]); r = np.array([1.0, 1.0], np.float32)
        m = ALS(rank=3, max_iter=2, reg_param=0.0).fit(u, i, r)
        assert np.isfinite(m.user_factors_).all()
        np.testing.assert_array_equal(m.user_factors_[1], 0.0)
        m2 = ALS(rank=3, max_iter=2, reg_param=0.0, implicit_prefs=True).fit(u, i, r)
        assert np.isfinite(m2.user_factors_).all()


class TestGroupedChunking:
    @pytest.mark.parametrize("implicit", [True, False])
    def test_chunked_partials_match_unchunked(self, rng, monkeypatch, implicit):
        """The G-blocked scan path (big sides that would OOM unchunked)
        returns bit-comparable moments to the single-shot path."""
        from oap_mllib_tpu.ops import als_ops

        nu, ni, nnz, rank = 50, 40, 600, 4
        u = rng.integers(nu, size=nnz).astype(np.int32)
        i = rng.integers(ni, size=nnz).astype(np.int32)
        r = (rng.random(nnz) * 4 + 1).astype(np.float32)
        import jax.numpy as jnp

        sg, cg, vg, gd = (
            jnp.asarray(a)
            for a in als_ops.build_grouped_edges(u, i, r, nu, group_size=8)
        )
        y = jnp.asarray(init_factors(ni, rank, 7))
        a1, b1, n1 = als_ops.normal_eq_partials_grouped(
            sg, cg, vg, gd, y, nu, 40.0, implicit
        )
        # force the scan path: a block budget far below this side's size
        # (odd block split so the dummy-group padding is exercised too)
        monkeypatch.setattr(als_ops, "_GROUPED_BUDGET_ELEMS", 8 * 8 * 6 * 3)
        assert als_ops._grouped_block_count(*sg.shape, rank) > 1
        a2, b2, n2 = als_ops.normal_eq_partials_grouped(
            sg, cg, vg, gd, y, nu, 40.0, implicit
        )
        np.testing.assert_allclose(np.asarray(a1), np.asarray(a2), atol=1e-4)
        np.testing.assert_allclose(np.asarray(b1), np.asarray(b2), atol=1e-4)
        np.testing.assert_allclose(np.asarray(n1), np.asarray(n2), atol=1e-6)


class TestBlockParallel:
    """The distributed 2-D block path (shuffle + shard_map) must agree with
    the single-program path and the NumPy oracle. Runs 8-way SPMD."""

    @pytest.mark.parametrize("implicit", [True, False])
    def test_block_path_used_and_matches_oracle(self, rng, implicit):
        u, i, r, nu, ni = _ratings(rng, n_users=50, n_items=30)
        rank, iters, reg, alpha = 5, 3, 0.1, 1.5
        x0 = init_factors(nu, rank, 1)
        y0 = init_factors(ni, rank, 2)
        model = ALS(
            rank=rank, max_iter=iters, reg_param=reg, alpha=alpha,
            implicit_prefs=implicit,
        ).fit(u, i, r, n_users=nu, n_items=ni, init=(x0, y0))
        assert model.summary.get("block_parallel"), "block path not taken on multi-device mesh"
        ox, oy = _oracle_als(u, i, r, nu, ni, rank, iters, reg, alpha, implicit, x0, y0)
        np.testing.assert_allclose(model.user_factors_, ox, atol=2e-3, rtol=2e-3)
        np.testing.assert_allclose(model.item_factors_, oy, atol=2e-3, rtol=2e-3)

    def test_block_vs_global_program(self, rng):
        """Block-parallel and GSPMD single-program paths agree."""
        from oap_mllib_tpu.ops import als_ops
        import jax.numpy as jnp

        u, i, r, nu, ni = _ratings(rng, n_users=33, n_items=17, density=0.4)
        rank, iters = 4, 2
        x0 = init_factors(nu, rank, 3)
        y0 = init_factors(ni, rank, 4)
        xg, yg = als_ops.als_implicit_run(
            jnp.asarray(u.astype(np.int32)), jnp.asarray(i.astype(np.int32)),
            jnp.asarray(r), jnp.ones_like(jnp.asarray(r)),
            jnp.asarray(x0), jnp.asarray(y0), nu, ni, iters, 0.2, 1.0,
        )
        model = ALS(rank=rank, max_iter=iters, reg_param=0.2, alpha=1.0,
                    implicit_prefs=True).fit(u, i, r, n_users=nu, n_items=ni,
                                             init=(x0, y0))
        np.testing.assert_allclose(model.user_factors_, np.asarray(xg), atol=2e-3, rtol=2e-3)
        np.testing.assert_allclose(model.item_factors_, np.asarray(yg), atol=2e-3, rtol=2e-3)

    def test_grouped_partials_match_coo(self, rng):
        """The scatter-free grouped layout and the COO segment-sum path
        compute identical normal-equation partials (both modes)."""
        import jax.numpy as jnp

        from oap_mllib_tpu.ops import als_ops

        u, i, r, nu, ni = _ratings(rng, n_users=23, n_items=11, density=0.5)
        src = rng.normal(size=(ni, 4)).astype(np.float32)
        for implicit in (True, False):
            a1, b1, n1 = als_ops.normal_eq_partials(
                jnp.asarray(u.astype(np.int32)), jnp.asarray(i.astype(np.int32)),
                jnp.asarray(r), jnp.ones(len(r), np.float32),
                jnp.asarray(src), nu, 7.0, implicit,
            )
            sg, cg, vg, gd = als_ops.build_grouped_edges(u, i, r, nu, group_size=8)
            a2, b2, n2 = als_ops.normal_eq_partials_grouped(
                jnp.asarray(sg), jnp.asarray(cg), jnp.asarray(vg),
                jnp.asarray(gd), jnp.asarray(src), nu, 7.0, implicit,
            )
            np.testing.assert_allclose(np.asarray(a1), np.asarray(a2), atol=1e-4)
            np.testing.assert_allclose(np.asarray(b1), np.asarray(b2), atol=1e-4)
            np.testing.assert_allclose(np.asarray(n1), np.asarray(n2), atol=1e-5)

    def test_grouped_run_matches_coo_programs(self, rng):
        """Full grouped training loop vs the COO reference programs."""
        import jax.numpy as jnp

        from oap_mllib_tpu.ops import als_ops

        u, i, r, nu, ni = _ratings(rng, n_users=19, n_items=13, density=0.4)
        rank, iters = 4, 3
        x0 = jnp.asarray(init_factors(nu, rank, 5))
        y0 = jnp.asarray(init_factors(ni, rank, 6))
        by_u = tuple(jnp.asarray(a) for a in als_ops.build_grouped_edges(u, i, r, nu))
        by_i = tuple(jnp.asarray(a) for a in als_ops.build_grouped_edges(i, u, r, ni))
        uj = jnp.asarray(u.astype(np.int32)); ij = jnp.asarray(i.astype(np.int32))
        rj = jnp.asarray(r); vj = jnp.ones(len(r), np.float32)
        # implicit
        xg, yg = als_ops.als_run_grouped(
            *by_u, *by_i, x0, y0, nu, ni, iters, 0.15, 3.0, True)
        xc, yc = als_ops.als_implicit_run(
            uj, ij, rj, vj, x0, y0, nu, ni, iters, 0.15, 3.0)
        np.testing.assert_allclose(np.asarray(xg), np.asarray(xc), atol=2e-4, rtol=2e-4)
        np.testing.assert_allclose(np.asarray(yg), np.asarray(yc), atol=2e-4, rtol=2e-4)
        # explicit
        xg, yg = als_ops.als_run_grouped(
            *by_u, *by_i, x0, y0, nu, ni, iters, 0.15, 0.0, False)
        xc, yc = als_ops.als_explicit_run(
            uj, ij, rj, vj, x0, y0, nu, ni, iters, 0.15)
        np.testing.assert_allclose(np.asarray(xg), np.asarray(xc), atol=2e-4, rtol=2e-4)
        np.testing.assert_allclose(np.asarray(yg), np.asarray(yc), atol=2e-4, rtol=2e-4)

    @pytest.mark.parametrize("implicit", [True, False])
    def test_single_device_grouped_estimator_matches_oracle(self, rng, implicit):
        """ALS with num_user_blocks=1 takes the single-device grouped path
        (even on the 8-device suite mesh) and matches the oracle."""
        u, i, r, nu, ni = _ratings(rng)
        rank, iters, reg, alpha = 4, 3, 0.2, 2.0
        x0 = init_factors(nu, rank, 1)
        y0 = init_factors(ni, rank, 2)
        model = ALS(
            rank=rank, max_iter=iters, reg_param=reg, alpha=alpha,
            implicit_prefs=implicit, num_user_blocks=1,
        ).fit(u, i, r, n_users=nu, n_items=ni, init=(x0, y0))
        assert not model.summary.get("block_parallel")
        ox = _oracle_als(u, i, r, nu, ni, rank, iters, reg, alpha, implicit, x0, y0)
        np.testing.assert_allclose(model.user_factors_, ox[0], atol=2e-3, rtol=2e-3)
        np.testing.assert_allclose(model.item_factors_, ox[1], atol=2e-3, rtol=2e-3)

    def test_long_tail_falls_back_to_coo(self, rng):
        """Degree ~1 everywhere: grouped padding would blow past the 6x
        guard, so the single-device fit must route to the COO programs
        and still match the oracle."""
        from oap_mllib_tpu.ops import als_ops

        nu = ni = 120
        u = np.arange(nu, dtype=np.int64)
        i = rng.permutation(ni).astype(np.int64)
        r = rng.integers(1, 6, size=nu).astype(np.float32)
        assert als_ops.auto_group_size(len(u), nu) == 8
        by_u = als_ops.build_grouped_edges(u, i, r, nu)
        by_i = als_ops.build_grouped_edges(i, u, r, ni)
        assert by_u[0].size + by_i[0].size > 6 * len(u)  # guard trips
        x0 = init_factors(nu, 3, 1)
        y0 = init_factors(ni, 3, 2)
        model = ALS(rank=3, max_iter=2, reg_param=0.1, num_user_blocks=1).fit(
            u, i, r, n_users=nu, n_items=ni, init=(x0, y0))
        ox, oy = _oracle_als(u, i, r, nu, ni, 3, 2, 0.1, 1.0, False, x0, y0)
        np.testing.assert_allclose(model.user_factors_, ox, atol=2e-3, rtol=2e-3)

    @pytest.mark.parametrize("implicit", [True, False])
    def test_block_grouped_matches_block_coo(self, rng, implicit):
        """The grouped-edge block path (scatter-free per-rank layouts) and
        the COO block path produce the same factors on the 8-way mesh."""
        u, i, r, nu, ni = _ratings(rng, n_users=50, n_items=30)
        x0 = init_factors(nu, 4, 5)
        y0 = init_factors(ni, 4, 6)
        kw = dict(rank=4, max_iter=3, reg_param=0.1, alpha=1.2,
                  implicit_prefs=implicit)
        set_config(als_kernel="grouped")
        mg = ALS(**kw).fit(u, i, r, n_users=nu, n_items=ni, init=(x0, y0))
        assert mg.summary.get("block_parallel")
        assert mg.summary["als_kernel"] == "grouped"
        set_config(als_kernel="coo")
        mc = ALS(**kw).fit(u, i, r, n_users=nu, n_items=ni, init=(x0, y0))
        assert mc.summary["als_kernel"] == "coo"
        np.testing.assert_allclose(
            mg.user_factors_, mc.user_factors_, atol=2e-3, rtol=2e-3
        )
        np.testing.assert_allclose(
            mg.item_factors_, mc.item_factors_, atol=2e-3, rtol=2e-3
        )
        # and both agree with the independent oracle
        ox, oy = _oracle_als(u, i, r, nu, ni, 4, 3, 0.1, 1.2, implicit, x0, y0)
        np.testing.assert_allclose(mg.user_factors_, ox, atol=2e-3, rtol=2e-3)
        np.testing.assert_allclose(mg.item_factors_, oy, atol=2e-3, rtol=2e-3)

    def test_block_long_tail_falls_back_to_coo(self, rng):
        """Degree ~1 everywhere on the multi-device mesh: the pre-shuffle
        block_grouped_guard must decide COO and the fit must route to the
        COO block program — and still match the oracle."""
        nu = ni = 120
        u = np.arange(nu, dtype=np.int64)
        i = rng.permutation(ni).astype(np.int64)
        r = rng.integers(1, 6, size=nu).astype(np.float32)
        x0 = init_factors(nu, 3, 1)
        y0 = init_factors(ni, 3, 2)
        model = ALS(rank=3, max_iter=2, reg_param=0.1).fit(
            u, i, r, n_users=nu, n_items=ni, init=(x0, y0)
        )
        assert model.summary.get("block_parallel")
        assert model.summary["als_kernel"] == "coo"
        ox, _ = _oracle_als(u, i, r, nu, ni, 3, 2, 0.1, 1.0, False, x0, y0)
        np.testing.assert_allclose(model.user_factors_, ox, atol=2e-3, rtol=2e-3)

    def test_block_skewed_head_falls_back_to_coo(self, rng):
        """Power-law head concentrated in ONE user block: the guard must
        price the REALIZED layout (every rank padded to the global max
        group counts, world * max_b) — a sum over blocks would approve
        this dataset and then materialize ~8x its estimate."""
        from oap_mllib_tpu.ops.als_block import block_grouped_guard

        nu, ni = 80, 600
        u = rng.integers(0, 10, 2000).astype(np.int64)  # all in block 0
        i = rng.integers(0, ni, 2000).astype(np.int64)
        r = rng.integers(1, 6, 2000).astype(np.float32)
        ok, _ = block_grouped_guard(u, i, nu, ni, 8)
        assert not ok
        model = ALS(rank=3, max_iter=1, implicit_prefs=True).fit(
            u, i, r, n_users=nu, n_items=ni
        )
        assert model.summary["als_kernel"] == "coo"

    def test_invalid_als_kernel_raises_on_block_path(self, rng):
        """A typo'd als_kernel must raise on the multi-device mesh too,
        never silently fall back to the auto heuristic."""
        u, i, r, nu, ni = _ratings(rng, n_users=20, n_items=10)
        set_config(als_kernel="groupd")
        with pytest.raises(ValueError, match="als_kernel"):
            ALS(rank=3, max_iter=1).fit(u, i, r, n_users=nu, n_items=ni)

    def test_users_fewer_than_ranks(self, rng):
        """Degenerate: fewer users than mesh ranks (empty blocks)."""
        u = np.array([0, 1, 2, 0, 1])
        i = np.array([0, 1, 2, 2, 0])
        r = np.ones(5, np.float32)
        model = ALS(rank=3, max_iter=2, implicit_prefs=True).fit(
            u, i, r, n_users=3, n_items=3)
        assert model.user_factors_.shape == (3, 3)
        assert np.isfinite(model.user_factors_).all()


class TestItemSharded:
    """The 2-D item-sharded layout (als_item_layout="sharded": Y
    block-sharded, all_gather exchanges — the reference's per-rank
    transposed item blocks, ALSDALImpl.cpp:192-214,301-316) must match
    the replicated-Y layout and the oracle bit-for-tolerance.  8-way
    SPMD via the suite mesh."""

    @pytest.mark.parametrize("kernel", ["grouped", "coo"])
    @pytest.mark.parametrize("implicit", [True, False])
    def test_sharded_matches_replicated(self, rng, kernel, implicit):
        u, i, r, nu, ni = _ratings(rng, n_users=50, n_items=30)
        x0 = init_factors(nu, 4, 5)
        y0 = init_factors(ni, 4, 6)
        kw = dict(rank=4, max_iter=3, reg_param=0.1, alpha=1.2,
                  implicit_prefs=implicit)
        set_config(als_kernel=kernel, als_item_layout="replicated")
        m1 = ALS(**kw).fit(u, i, r, n_users=nu, n_items=ni, init=(x0, y0))
        assert m1.summary["item_layout"] == "replicated"
        set_config(als_item_layout="sharded")
        m2 = ALS(**kw).fit(u, i, r, n_users=nu, n_items=ni, init=(x0, y0))
        assert m2.summary["item_layout"] == "sharded"
        assert m2.summary["als_kernel"] == kernel
        np.testing.assert_allclose(
            m1.user_factors_, m2.user_factors_, atol=2e-4, rtol=2e-4
        )
        np.testing.assert_allclose(
            m1.item_factors_, m2.item_factors_, atol=2e-4, rtol=2e-4
        )

    @pytest.mark.parametrize("implicit", [True, False])
    def test_sharded_matches_oracle(self, rng, implicit):
        u, i, r, nu, ni = _ratings(rng, n_users=41, n_items=23)
        rank, iters, reg, alpha = 5, 3, 0.15, 1.5
        x0 = init_factors(nu, rank, 1)
        y0 = init_factors(ni, rank, 2)
        set_config(als_item_layout="sharded")
        model = ALS(
            rank=rank, max_iter=iters, reg_param=reg, alpha=alpha,
            implicit_prefs=implicit,
        ).fit(u, i, r, n_users=nu, n_items=ni, init=(x0, y0))
        assert model.summary["item_layout"] == "sharded"
        ox, oy = _oracle_als(u, i, r, nu, ni, rank, iters, reg, alpha,
                             implicit, x0, y0)
        np.testing.assert_allclose(model.user_factors_, ox, atol=2e-3, rtol=2e-3)
        np.testing.assert_allclose(model.item_factors_, oy, atol=2e-3, rtol=2e-3)

    def test_items_fewer_than_ranks(self, rng):
        """n_items < world: empty item blocks on most ranks must still
        produce finite factors identical to the replicated layout."""
        u = rng.integers(0, 40, 500).astype(np.int64)
        i = rng.integers(0, 5, 500).astype(np.int64)
        r = rng.integers(1, 6, 500).astype(np.float32)
        x0 = init_factors(40, 3, 1)
        y0 = init_factors(5, 3, 2)
        set_config(als_item_layout="sharded")
        ms = ALS(rank=3, max_iter=2).fit(u, i, r, n_users=40, n_items=5,
                                         init=(x0, y0))
        set_config(als_item_layout="replicated")
        mr = ALS(rank=3, max_iter=2).fit(u, i, r, n_users=40, n_items=5,
                                         init=(x0, y0))
        assert ms.item_factors_.shape == (5, 3)
        assert np.isfinite(ms.item_factors_).all()
        np.testing.assert_allclose(
            ms.item_factors_, mr.item_factors_, atol=2e-4, rtol=2e-4
        )

    def test_default_init_matches_replicated(self, rng):
        """Without a user-supplied init, the sharded path's per-block
        position-addressable Y init must reproduce the replicated init
        rows exactly (same generator, different placement)."""
        u, i, r, nu, ni = _ratings(rng, n_users=30, n_items=26)
        set_config(als_item_layout="sharded")
        ms = ALS(rank=4, max_iter=2, seed=9).fit(u, i, r, n_users=nu, n_items=ni)
        set_config(als_item_layout="replicated")
        mr = ALS(rank=4, max_iter=2, seed=9).fit(u, i, r, n_users=nu, n_items=ni)
        np.testing.assert_allclose(
            ms.item_factors_, mr.item_factors_, atol=2e-4, rtol=2e-4
        )

    def test_invalid_layout_raises(self, rng):
        u, i, r, nu, ni = _ratings(rng, n_users=20, n_items=10)
        set_config(als_item_layout="shraded")
        with pytest.raises(ValueError, match="als_item_layout"):
            ALS(rank=3, max_iter=1).fit(u, i, r, n_users=nu, n_items=ni)
        # single-device path too (num_user_blocks=1): the knob has no
        # layout effect there, but a typo must still raise — it must not
        # surface only once deployed to a mesh
        with pytest.raises(ValueError, match="als_item_layout"):
            ALS(rank=3, max_iter=1, num_user_blocks=1).fit(
                u, i, r, n_users=nu, n_items=ni
            )

    def test_auto_crossover_rule(self):
        """auto = shard only past the psum-bytes bound, and never on a
        1-wide data axis."""
        from oap_mllib_tpu.ops.als_block import (
            ITEM_SHARD_AUTO_BYTES,
            item_layout_sharded,
        )

        r = 10
        big = ITEM_SHARD_AUTO_BYTES // (r * (r + 1) * 4) + 1
        set_config(als_item_layout="auto")
        assert not item_layout_sharded(1000, r, 8)
        assert item_layout_sharded(big, r, 8)
        assert not item_layout_sharded(big, r, 1)  # no mesh to shard over
        # user-dominated past the traffic crossover (n_users > (2r+1) x
        # n_items): the X all_gather would outweigh the psum — stay
        # replicated even above the payload threshold
        assert item_layout_sharded(big, r, 8, n_users=big * (2 * r + 1))
        assert not item_layout_sharded(
            big, r, 8, n_users=big * (2 * r + 1) + 1
        )
        set_config(als_item_layout="sharded")
        assert item_layout_sharded(10, r, 8)
        set_config(als_item_layout="replicated")
        assert not item_layout_sharded(big, r, 8)

    def test_save_load_roundtrip_sharded(self, tmp_path, rng):
        """save gathers the sharded Y; load restores a host model with
        identical predictions."""
        u, i, r, nu, ni = _ratings(rng)
        set_config(als_item_layout="sharded")
        m = ALS(rank=4, max_iter=2).fit(u, i, r, n_users=nu, n_items=ni)
        path = str(tmp_path / "als_sharded")
        m.save(path)
        m2 = ALSModel.load(path)
        np.testing.assert_allclose(m2.item_factors_, m.item_factors_)
        np.testing.assert_allclose(m2.predict(u, i), m.predict(u, i))

    def test_sharded_on_model_parallel_mesh(self, rng):
        """als_item_layout="sharded" composes with model_parallel: the
        (data=4, model=2) mesh replicates the block arrays over the
        model axis and the data-axis all_gathers/psums still produce
        the single-mesh factors."""
        u, i, r, nu, ni = _ratings(rng, n_users=40, n_items=24)
        x0 = init_factors(nu, 3, 1)
        y0 = init_factors(ni, 3, 2)
        set_config(als_item_layout="sharded")
        m1 = ALS(rank=3, max_iter=2).fit(u, i, r, n_users=nu, n_items=ni,
                                         init=(x0, y0))
        set_config(model_parallel=2)
        m2 = ALS(rank=3, max_iter=2).fit(u, i, r, n_users=nu, n_items=ni,
                                         init=(x0, y0))
        assert m2.summary["item_layout"] == "sharded"
        assert m2.summary["num_user_blocks"] == 4  # data axis shrank
        np.testing.assert_allclose(
            m1.item_factors_, m2.item_factors_, atol=2e-4, rtol=2e-4
        )
        np.testing.assert_allclose(
            m1.user_factors_, m2.user_factors_, atol=2e-4, rtol=2e-4
        )

    def test_sharded_long_tail_falls_back_to_coo(self, rng):
        """Degree ~1: block_grouped_guard_2d must decide COO on the
        sharded path too, and the COO 2-D program must match the
        oracle."""
        nu = ni = 120
        u = np.arange(nu, dtype=np.int64)
        i = rng.permutation(ni).astype(np.int64)
        r = rng.integers(1, 6, size=nu).astype(np.float32)
        x0 = init_factors(nu, 3, 1)
        y0 = init_factors(ni, 3, 2)
        set_config(als_item_layout="sharded")
        model = ALS(rank=3, max_iter=2, reg_param=0.1).fit(
            u, i, r, n_users=nu, n_items=ni, init=(x0, y0)
        )
        assert model.summary["als_kernel"] == "coo"
        assert model.summary["item_layout"] == "sharded"
        ox, oy = _oracle_als(u, i, r, nu, ni, 3, 2, 0.1, 1.0, False, x0, y0)
        np.testing.assert_allclose(model.user_factors_, ox, atol=2e-3, rtol=2e-3)
        np.testing.assert_allclose(model.item_factors_, oy, atol=2e-3, rtol=2e-3)


class TestStreamedALS:
    """Out-of-core ALS (ops/als_stream.py): a width-3 (user, item,
    rating) ChunkSource fit must match the in-memory fit — same grouped
    math, host-chunked device uploads.  The suite mesh has 8 devices, so
    the single-device streamed path is pinned via num_user_blocks=1."""

    def _triples_source(self, u, i, r, chunk_rows):
        from oap_mllib_tpu.data.stream import ChunkSource

        trip = np.stack(
            [u.astype(np.float64), i.astype(np.float64),
             r.astype(np.float64)], axis=1,
        )
        return ChunkSource.from_array(trip, chunk_rows=chunk_rows)

    @pytest.mark.parametrize("implicit", [True, False])
    def test_streamed_matches_in_memory(self, rng, implicit):
        u, i, r, nu, ni = _ratings(rng, n_users=50, n_items=30)
        x0 = init_factors(nu, 4, 1)
        y0 = init_factors(ni, 4, 2)
        kw = dict(rank=4, max_iter=3, reg_param=0.1, alpha=1.2,
                  implicit_prefs=implicit, num_user_blocks=1)
        m1 = ALS(**kw).fit(u, i, r, n_users=nu, n_items=ni, init=(x0, y0))
        m2 = ALS(**kw).fit(
            self._triples_source(u, i, r, 137),
            n_users=nu, n_items=ni, init=(x0, y0),
        )
        assert m2.summary.get("streamed")
        assert m2.summary["als_kernel"] == "grouped"
        np.testing.assert_allclose(
            m1.user_factors_, m2.user_factors_, atol=1e-4, rtol=1e-4
        )
        np.testing.assert_allclose(
            m1.item_factors_, m2.item_factors_, atol=1e-4, rtol=1e-4
        )

    def test_streamed_parity_fuzz(self, rng):
        """Random shapes x chunkings (mirroring tests/test_stream.py's
        streamed-vs-in-memory fuzz): every draw must match the in-memory
        fit on the same init."""
        for trial in range(4):
            nu = int(rng.integers(5, 60))
            ni = int(rng.integers(5, 50))
            nnz = int(rng.integers(20, 800))
            u = rng.integers(0, nu, nnz)
            i = rng.integers(0, ni, nnz)
            r = (rng.random(nnz) * 4 + 1).astype(np.float32)
            chunk = int(rng.integers(8, 512))
            implicit = bool(rng.integers(2))
            x0 = init_factors(nu, 3, trial)
            y0 = init_factors(ni, 3, trial + 100)
            kw = dict(rank=3, max_iter=2, reg_param=0.15, alpha=0.7,
                      implicit_prefs=implicit, num_user_blocks=1)
            m1 = ALS(**kw).fit(u, i, r, n_users=nu, n_items=ni,
                               init=(x0, y0))
            m2 = ALS(**kw).fit(
                self._triples_source(u, i, r, chunk),
                n_users=nu, n_items=ni, init=(x0, y0),
            )
            np.testing.assert_allclose(
                m1.user_factors_, m2.user_factors_, atol=1e-4, rtol=1e-4,
                err_msg=f"trial {trial}: nu={nu} ni={ni} nnz={nnz} "
                        f"chunk={chunk} implicit={implicit}",
            )

    def test_streamed_small_chunks_stress(self, rng):
        """Chunk smaller than one group's width and a tiny upload budget
        (monkeypatched groups_per_chunk) — many uploads per side."""
        from oap_mllib_tpu.ops import als_stream

        u, i, r, nu, ni = _ratings(rng, n_users=30, n_items=20)
        x0 = init_factors(nu, 3, 1)
        y0 = init_factors(ni, 3, 2)
        kw = dict(rank=3, max_iter=2, num_user_blocks=1)
        m1 = ALS(**kw).fit(u, i, r, n_users=nu, n_items=ni, init=(x0, y0))
        orig = als_stream.groups_per_chunk
        als_stream.groups_per_chunk = lambda P, r_: 2
        try:
            m2 = ALS(**kw).fit(
                self._triples_source(u, i, r, 16),
                n_users=nu, n_items=ni, init=(x0, y0),
            )
        finally:
            als_stream.groups_per_chunk = orig
        np.testing.assert_allclose(
            m1.user_factors_, m2.user_factors_, atol=1e-4, rtol=1e-4
        )

    def test_streamed_composes_with_mesh(self, rng):
        """On the 8-device suite mesh the source fit COMPOSES streaming
        with the block layout (ops/als_block_stream.py) — per-rank
        host-resident grouped layouts, chunked uploads, the block path's
        collectives — instead of falling back to fully-resident device
        layouts (the round-4 review gap).  Factors must match the
        in-memory block fit on the same init."""
        u, i, r, nu, ni = _ratings(rng)
        x0 = init_factors(nu, 3, 1)
        y0 = init_factors(ni, 3, 2)
        kw = dict(rank=3, max_iter=2, reg_param=0.1, alpha=0.9)
        # force grouped: the test dataset is small enough that the block
        # guard would price 8-block padding above the COO crossover
        set_config(als_kernel="grouped")
        try:
            m1 = ALS(**kw).fit(u, i, r, n_users=nu, n_items=ni,
                               init=(x0, y0))
            m2 = ALS(**kw).fit(
                self._triples_source(u, i, r, 128), n_users=nu,
                n_items=ni, init=(x0, y0),
            )
        finally:
            set_config(als_kernel="auto")
        assert m1.summary.get("block_parallel")
        assert m2.summary.get("block_parallel")
        assert m2.summary.get("streamed")
        assert m2.summary.get("sharded_factors")
        assert m2.summary["item_layout"] == "replicated"
        np.testing.assert_allclose(
            m1.user_factors_, m2.user_factors_, atol=1e-4, rtol=1e-4
        )
        np.testing.assert_allclose(
            m1.item_factors_, m2.item_factors_, atol=1e-4, rtol=1e-4
        )

    def test_streamed_weighted_block_offsets_parity(self, rng):
        """Capability-weighted user blocks on the STREAMED block path
        (ISSUE 15 carry-over): injected uneven offsets — monkeypatched
        ``balance.block_offsets``, the same planner seam the in-memory
        fit consults — must reproduce the uniform streamed fit's factors
        on the 8-device mesh.  The weighted layout only moves rows
        between blocks; searchsorted block mapping, block-local
        rebasing, factor placement and the gather-back are all
        boundary-generic."""
        from oap_mllib_tpu.parallel import balance

        u, i, r, nu, ni = _ratings(rng, n_users=53, n_items=24)
        x0 = init_factors(nu, 3, 1)
        y0 = init_factors(ni, 3, 2)
        kw = dict(rank=3, max_iter=2, reg_param=0.1, alpha=0.8)
        set_config(als_kernel="grouped")
        orig = balance.block_offsets
        try:
            m1 = ALS(**kw).fit(
                self._triples_source(u, i, r, 64), n_users=nu,
                n_items=ni, init=(x0, y0),
            )
            off = balance.plan_block_offsets(
                nu, [4.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 2.0]
            )
            assert off is not None and len(off) == 9
            assert len(set(np.diff(off))) > 1  # genuinely uneven blocks
            balance.block_offsets = lambda *a, **k: off
            m2 = ALS(**kw).fit(
                self._triples_source(u, i, r, 64), n_users=nu,
                n_items=ni, init=(x0, y0),
            )
        finally:
            balance.block_offsets = orig
            set_config(als_kernel="auto")
        assert m2.summary.get("streamed")
        assert m2.summary["item_layout"] == "replicated"
        np.testing.assert_allclose(
            m1.user_factors_, m2.user_factors_, atol=1e-5, rtol=1e-5
        )
        np.testing.assert_allclose(
            m1.item_factors_, m2.item_factors_, atol=1e-5, rtol=1e-5
        )

    @pytest.mark.parametrize("implicit", [True, False])
    def test_streamed_mesh_parity_item_sharded(self, rng, implicit):
        """Streamed-vs-in-memory parity on the mesh with the 2-D
        item-sharded layout (uneven n_users/n_items vs the 8 blocks, so
        the last blocks are short): both feedback modes."""
        u, i, r, nu, ni = _ratings(rng, n_users=53, n_items=37)
        x0 = init_factors(nu, 3, 1)
        y0 = init_factors(ni, 3, 2)
        kw = dict(rank=3, max_iter=2, reg_param=0.1, alpha=0.8,
                  implicit_prefs=implicit)
        set_config(als_item_layout="sharded")
        try:
            m1 = ALS(**kw).fit(u, i, r, n_users=nu, n_items=ni,
                               init=(x0, y0))
            m2 = ALS(**kw).fit(
                self._triples_source(u, i, r, 97), n_users=nu,
                n_items=ni, init=(x0, y0),
            )
        finally:
            set_config(als_item_layout="auto")
        assert m2.summary.get("streamed")
        assert m2.summary["item_layout"] == "sharded"
        np.testing.assert_allclose(
            m1.user_factors_, m2.user_factors_, atol=1e-4, rtol=1e-4
        )
        np.testing.assert_allclose(
            m1.item_factors_, m2.item_factors_, atol=1e-4, rtol=1e-4
        )

    def test_streamed_composes_with_model_parallel_mesh(self, rng):
        """The streamed block path on a (data=4, model=2) mesh: owned
        blocks are data-axis blocks (model replicas collapse), chunk
        placement replicates over the model axis, and the factors match
        the pure-data-parallel streamed fit."""
        u, i, r, nu, ni = _ratings(rng, n_users=40, n_items=24)
        x0 = init_factors(nu, 3, 1)
        y0 = init_factors(ni, 3, 2)
        kw = dict(rank=3, max_iter=2, reg_param=0.1)
        set_config(als_kernel="grouped")
        try:
            m1 = ALS(**kw).fit(
                self._triples_source(u, i, r, 64), n_users=nu,
                n_items=ni, init=(x0, y0),
            )
            set_config(model_parallel=2)
            m2 = ALS(**kw).fit(
                self._triples_source(u, i, r, 64), n_users=nu,
                n_items=ni, init=(x0, y0),
            )
        finally:
            set_config(model_parallel=1, als_kernel="auto")
        assert m2.summary.get("streamed") and m2.summary.get("block_parallel")
        assert m2.summary["num_user_blocks"] == 4  # data axis shrank
        np.testing.assert_allclose(
            m1.user_factors_, m2.user_factors_, atol=2e-4, rtol=2e-4
        )
        np.testing.assert_allclose(
            m1.item_factors_, m2.item_factors_, atol=2e-4, rtol=2e-4
        )

    def test_streamed_mesh_small_chunks(self, rng):
        """Tiny upload budget on the mesh path (monkeypatched
        groups_per_chunk -> many chunk launches per half-iteration)."""
        from oap_mllib_tpu.ops import als_block_stream

        u, i, r, nu, ni = _ratings(rng, n_users=30, n_items=20)
        x0 = init_factors(nu, 3, 1)
        y0 = init_factors(ni, 3, 2)
        kw = dict(rank=3, max_iter=2)
        set_config(als_kernel="grouped")  # see test_streamed_composes_with_mesh
        orig = als_block_stream.groups_per_chunk
        try:
            m1 = ALS(**kw).fit(u, i, r, n_users=nu, n_items=ni,
                               init=(x0, y0))
            als_block_stream.groups_per_chunk = lambda P, r_: 2
            m2 = ALS(**kw).fit(
                self._triples_source(u, i, r, 16),
                n_users=nu, n_items=ni, init=(x0, y0),
            )
        finally:
            als_block_stream.groups_per_chunk = orig
            set_config(als_kernel="auto")
        assert m2.summary.get("streamed")
        np.testing.assert_allclose(
            m1.user_factors_, m2.user_factors_, atol=1e-4, rtol=1e-4
        )
        np.testing.assert_allclose(
            m1.item_factors_, m2.item_factors_, atol=1e-4, rtol=1e-4
        )

    def test_streamed_long_tail_delegates_to_coo(self, rng):
        """Degree ~1: the grouped guard rejects, so the source fit falls
        back to the in-memory COO programs (flat-moment streaming is
        grouped-only) and still matches the oracle."""
        nu = ni = 120
        u = np.arange(nu, dtype=np.int64)
        i = rng.permutation(ni).astype(np.int64)
        r = rng.integers(1, 6, size=nu).astype(np.float32)
        x0 = init_factors(nu, 3, 1)
        y0 = init_factors(ni, 3, 2)
        m = ALS(rank=3, max_iter=2, reg_param=0.1, num_user_blocks=1).fit(
            self._triples_source(u, i, r, 64),
            n_users=nu, n_items=ni, init=(x0, y0),
        )
        assert m.summary["als_kernel"] == "coo"
        assert not m.summary.get("streamed")
        ox, _ = _oracle_als(u, i, r, nu, ni, 3, 2, 0.1, 1.0, False, x0, y0)
        np.testing.assert_allclose(m.user_factors_, ox, atol=2e-3, rtol=2e-3)

    def test_source_width_validation(self, rng):
        from oap_mllib_tpu.data.stream import ChunkSource

        src = ChunkSource.from_array(np.zeros((10, 2)), chunk_rows=4)
        with pytest.raises(ValueError, match="width 3"):
            ALS(rank=3).fit(src)
        with pytest.raises(ValueError, match="EITHER"):
            ALS(rank=3).fit(
                ChunkSource.from_array(np.zeros((10, 3)), chunk_rows=4),
                np.zeros(3, np.int64), np.zeros(3, np.float32),
            )
        with pytest.raises(TypeError, match="items and ratings"):
            ALS(rank=3).fit(np.zeros(3, np.int64))


class TestNonnegative:
    def test_nonnegative_factors(self, rng):
        u, i, r, nu, ni = _ratings(rng)
        m = ALS(rank=4, max_iter=5, reg_param=0.1, nonnegative=True).fit(u, i, r)
        assert not m.summary["accelerated"]  # NNLS runs on the fallback path
        assert (m.user_factors_ >= 0).all()
        assert (m.item_factors_ >= 0).all()
        # still fits: predictions correlate with ratings
        pred = m.predict(u, i)
        assert np.corrcoef(pred, r)[0, 1] > 0.3

    def test_nonnegative_implicit(self, rng):
        u, i, r, nu, ni = _ratings(rng, density=0.2)
        m = ALS(rank=4, max_iter=4, implicit_prefs=True, alpha=2.0,
                nonnegative=True).fit(u, i, r)
        assert (m.user_factors_ >= 0).all() and (m.item_factors_ >= 0).all()
