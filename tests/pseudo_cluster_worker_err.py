"""Error-injection worker: one rank's source fails mid-pass.

Validates the round-4 _PassGuard contract in a REAL ``jax.distributed``
world (not the in-process mock): rank 1's ChunkSource yields a different
row count on the second pass; without the guard, rank 0 would block in
``process_allgather`` until the distributed timeout while rank 1 exits.
With it, BOTH ranks must raise promptly — rank 1 with the original
ValueError chained, rank 0 with the collective RuntimeError.

Invoked as:  python pseudo_cluster_worker_err.py RANK NPROC COORD LOCAL_DEVICES
(the standard worker argv, so the shared _launch_world plumbing spawns it).
Exit code 0 = the expected error was raised on this rank (the parent
asserts all ranks exit 0 quickly); any other outcome exits nonzero.
"""

import sys

rank, nproc = int(sys.argv[1]), int(sys.argv[2])
coord, local_dev = sys.argv[3], int(sys.argv[4])

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    # older jax lines have no jax_num_cpu_devices config option; the env
    # flag must be in place before the backend initializes
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={local_dev}"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
if hasattr(jax.config, "jax_num_cpu_devices"):
    jax.config.update("jax_num_cpu_devices", local_dev)

import numpy as np

from oap_mllib_tpu.parallel import bootstrap

assert bootstrap.initialize_distributed(coord, nproc, rank)

from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.models.kmeans import KMeans

rng = np.random.default_rng(5)
x = rng.normal(size=(600, 8)).astype(np.float32)

if rank == 0:
    src = ChunkSource.from_array(x, chunk_rows=128)
else:
    # deterministic on pass 1, short by one row from pass 2 on —
    # ChunkSource's row-count check raises mid-pass on THIS rank only
    passes = {"n": 0}

    def gen():
        passes["n"] += 1
        rows = 600 if passes["n"] == 1 else 599
        yield x[:rows]

    src = ChunkSource(gen, n_features=8, chunk_rows=128)

try:
    # random init = 1 reservoir pass (consistent) + per-iteration passes;
    # rank 1's pass 2 errors, and the guard must carry it to the next
    # reduction so rank 0 fails the SAME fit call
    KMeans(k=4, seed=1, init_mode="random", max_iter=5).fit(src)
except (ValueError, RuntimeError) as e:
    cause = f" (cause: {e.__cause__})" if e.__cause__ is not None else ""
    print(
        f"EXPECTED_ERROR rank={rank} {type(e).__name__}: {e}{cause}",
        flush=True,
    )
    sys.exit(0)
print(f"NO_ERROR rank={rank} — fit succeeded but must not have", flush=True)
sys.exit(1)
