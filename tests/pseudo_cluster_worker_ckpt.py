"""Elastic-worlds pseudo-cluster worker (kill-and-resume leg, ISSUE 8).

One rank of a real ``jax.distributed`` world fitting streamed K-Means
with checkpointing armed.  Modes (env ``CKPT_WORKER_MODE``):

- ``full``    — uninterrupted checkpoint-armed fit; prints RESULT.
- ``victim``  — rank 1 hard-kills itself (``os._exit(9)``, no cleanup —
  a preemption) mid-read of Lloyd pass 3; passes 1–2 are durable on
  every rank (shards + manifest).  Rank 0 is left blocked in the pass
  collective; the parent kills it.
- ``resume``  — a RELAUNCHED world (fresh processes, same
  ``CKPT_CHECKPOINT_DIR``) resumes at the recorded pass and completes;
  prints RESULT.  The parent asserts RESULT equals the ``full`` run
  bit-for-bit (same world size ⇒ bit-identical continuation).
- ``resume1`` — a single-process relaunch path is exercised by the
  parent directly (world-size change), not via this worker.

Invoked as:  python pseudo_cluster_worker_ckpt.py RANK NPROC COORD LOCAL_DEV
"""

import os
import sys

rank, nproc = int(sys.argv[1]), int(sys.argv[2])
coord, local_dev = sys.argv[3], int(sys.argv[4])
mode = os.environ["CKPT_WORKER_MODE"]
ckdir = os.environ["CKPT_CHECKPOINT_DIR"]

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={local_dev}"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
if hasattr(jax.config, "jax_num_cpu_devices"):
    jax.config.update("jax_num_cpu_devices", local_dev)

import numpy as np

from oap_mllib_tpu.parallel import bootstrap

ran = bootstrap.initialize_distributed(coord, nproc, rank)
assert ran, "initialize_distributed returned False"

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.models.kmeans import KMeans

# deterministic global dataset, each rank streams its own half (matches
# tests/test_pseudo_cluster.py::TestElasticWorlds oracle)
rng = np.random.default_rng(321)
x = rng.normal(size=(3000, 8)).astype(np.float32)
shard = x[rank * 1500 : (rank + 1) * 1500]

walks = {"n": 0}


def gen():
    walks["n"] += 1
    # walk 1 = the random-init reservoir pass; Lloyd passes are walks
    # 2+.  The victim rank dies mid-read of Lloyd pass 3 (walk 4) —
    # passes 1 and 2 are checkpointed durably on every rank.
    if mode == "victim" and rank == 1 and walks["n"] == 4:
        os._exit(9)
    for lo in range(0, shard.shape[0], 500):
        yield shard[lo : lo + 500]


src = ChunkSource(gen, shard.shape[1], 500, n_rows=shard.shape[0])
set_config(checkpoint_dir=ckdir)
m = KMeans(k=4, seed=7, init_mode="random", max_iter=6, tol=0.0).fit(src)
ck = m.summary.checkpoint
import json

print(
    "RESULT "
    + json.dumps({
        "rank": rank,
        "cost": float(m.summary.training_cost),
        "centers_hex": np.ascontiguousarray(
            m.cluster_centers_
        ).tobytes().hex(),
        "decision": ck["decision"],
        "restored_step": ck["restored_step"],
        "ladder": m.summary.resilience["ladder"],
    }),
    flush=True,
)
