"""Out-of-core streaming tests: ChunkSource + streamed K-Means / PCA.

The streamed paths must match the in-memory accelerated paths (same math,
different pass structure) — ops-level parity is exact-ish (same init),
estimator-level parity is blob-recovery/cost-based because the streamed
init RNG (reservoir) legitimately differs from the in-memory one
(survey §7.3: RNG-sensitive init is compared by cost, not centers).
"""

import os

import numpy as np
import pytest

from oap_mllib_tpu import KMeans, PCA
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.data.stream import ChunkSource

HERE = os.path.dirname(os.path.abspath(__file__))
DATA = os.path.join(HERE, "..", "examples", "data")


def _reconstruct(source):
    return source.to_array()


class TestChunkSource:
    def test_from_array_round_trip(self, rng):
        x = rng.normal(size=(1000, 7))
        src = ChunkSource.from_array(x, chunk_rows=128)
        got = _reconstruct(src)
        np.testing.assert_allclose(got, x)
        assert src.n_rows == 1000
        # every chunk has the static shape; the last one is padded
        shapes = [(c.shape, v) for c, v in src]
        assert all(s == (128, 7) for s, _ in shapes)
        assert shapes[-1][1] == 1000 - 7 * 128

    def test_reiterable(self, rng):
        x = rng.normal(size=(300, 3))
        src = ChunkSource.from_array(x, chunk_rows=100)
        a = _reconstruct(src)
        b = _reconstruct(src)
        np.testing.assert_allclose(a, b)

    def test_chunk_bigger_than_data(self, rng):
        x = rng.normal(size=(10, 4))
        src = ChunkSource.from_array(x, chunk_rows=64)
        chunks = list(src)
        assert len(chunks) == 1
        assert chunks[0][0].shape == (64, 4)
        assert chunks[0][1] == 10

    def test_csv_matches_eager_reader(self):
        from oap_mllib_tpu.data.io import read_csv

        path = os.path.join(DATA, "pca_data.csv")
        eager = read_csv(path)
        src = ChunkSource.from_csv(path, chunk_rows=7)
        np.testing.assert_allclose(_reconstruct(src), eager)
        assert src.n_rows == eager.shape[0]

    def test_libsvm_matches_eager_reader(self):
        from oap_mllib_tpu.data.io import read_libsvm

        path = os.path.join(DATA, "sample_kmeans_data.txt")
        _, eager = read_libsvm(path)
        src = ChunkSource.from_libsvm(path, eager.shape[1], chunk_rows=5)
        np.testing.assert_allclose(_reconstruct(src), eager)

    def test_width_mismatch_raises(self, rng):
        src = ChunkSource(lambda: iter([np.zeros((4, 3))]), n_features=5)
        with pytest.raises(ValueError, match="width"):
            list(src)

    def test_nondeterministic_source_raises(self):
        counts = iter([10, 9])

        def gen():
            yield np.zeros((next(counts), 2))

        src = ChunkSource(gen, n_features=2, chunk_rows=8)
        list(src)
        with pytest.raises(ValueError, match="deterministic"):
            list(src)

    def test_source_error_surfaces_through_streamed_pass(self, rng):
        """A source that errors mid-fit must raise (via _PassGuard) out of
        the streamed kernel, not be swallowed — single-process the
        original exception type/message is preserved."""
        from oap_mllib_tpu.ops import stream_ops

        counts = iter([10, 9])  # pass 2 disagrees with pass 1

        def gen():
            yield np.zeros((next(counts), 3))

        src = ChunkSource(gen, n_features=3, chunk_rows=8)
        centers = np.zeros((2, 3), np.float32)
        stream_ops.streamed_accumulate(  # pass 1 fixes n_rows=10
            src, np.asarray(centers), np.float32, "highest", need_cost=False
        )
        with pytest.raises(ValueError, match="deterministic"):
            stream_ops.streamed_accumulate(
                src, np.asarray(centers), np.float32, "highest",
                need_cost=False,
            )

    def test_pass_guard_reraises_at_reduction(self):
        """_PassGuard swallows inside the with-block and the next
        reduction re-raises — the mechanism that keeps multi-host ranks
        from hanging in process_allgather when a peer's source fails."""
        from oap_mllib_tpu.ops import stream_ops

        guard = stream_ops._PassGuard()
        with guard:
            raise ValueError("boom mid-pass")
        assert isinstance(guard.err, ValueError)
        with pytest.raises(ValueError, match="boom mid-pass"):
            stream_ops._psum_host([np.zeros(3)], guard=guard)
        with pytest.raises(ValueError, match="boom mid-pass"):
            stream_ops._allgather_host([np.zeros(3)], guard=guard)
        # clean guard: reductions pass through untouched
        ok = stream_ops._PassGuard()
        with ok:
            pass
        (out,) = stream_ops._psum_host([np.ones(3)], guard=ok)
        np.testing.assert_allclose(out, np.ones(3))


class TestStreamedOps:
    def test_lloyd_streamed_matches_in_memory(self, rng):
        """Same init, same data: streamed Lloyd == one-shot Lloyd."""
        import jax.numpy as jnp

        from oap_mllib_tpu.ops import kmeans_ops, stream_ops

        x = rng.normal(size=(999, 12)).astype(np.float32)
        init = x[rng.choice(999, 5, replace=False)]
        c1, i1, t1, n1 = kmeans_ops.lloyd_run(
            jnp.asarray(x), jnp.ones((999,), jnp.float32), jnp.asarray(init),
            15, jnp.asarray(1e-6, jnp.float32),
        )
        src = ChunkSource.from_array(x, chunk_rows=256)
        c2, i2, t2, n2 = stream_ops.lloyd_run_streamed(
            src, init, 15, 1e-6, np.float32
        )
        assert int(i1) == int(i2)
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), atol=1e-4)
        np.testing.assert_allclose(float(t1), float(t2), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(n1), np.asarray(n2), atol=1e-5)

    def test_covariance_streamed_matches_in_memory(self, rng):
        import jax.numpy as jnp

        from oap_mllib_tpu.ops import pca_ops, stream_ops

        x = rng.normal(size=(500, 9)).astype(np.float32) + 3.0
        cov1, mean1 = pca_ops.covariance(
            jnp.asarray(x), jnp.ones((500,), jnp.float32),
            jnp.asarray(500.0, jnp.float32),
        )
        src = ChunkSource.from_array(x, chunk_rows=128)
        cov2, mean2, n = stream_ops.covariance_streamed(src, np.float32)
        assert n == 500
        np.testing.assert_allclose(np.asarray(mean1), np.asarray(mean2), atol=1e-5)
        np.testing.assert_allclose(np.asarray(cov1), np.asarray(cov2), atol=1e-4)

    def test_streamed_matches_in_memory_fuzz(self, rng):
        """Randomized shapes/chunk sizes: streamed Lloyd and covariance
        must match their in-memory counterparts for any chunking."""
        import jax.numpy as jnp

        from oap_mllib_tpu.ops import kmeans_ops, pca_ops, stream_ops

        for trial in range(6):
            n = int(rng.integers(3, 700))
            d = int(rng.integers(1, 20))
            k = int(rng.integers(1, min(6, n) + 1))
            chunk = int(rng.integers(1, n + 8))
            x = rng.normal(size=(n, d)).astype(np.float32) * 3
            src = ChunkSource.from_array(x, chunk_rows=chunk)
            init = x[rng.choice(n, k, replace=False)]
            c1, i1, t1, n1 = kmeans_ops.lloyd_run(
                jnp.asarray(x), jnp.ones((n,), jnp.float32),
                jnp.asarray(init), 8, jnp.asarray(1e-6, jnp.float32),
            )
            c2, i2, t2, n2 = stream_ops.lloyd_run_streamed(
                src, init, 8, 1e-6, np.float32
            )
            ctx = f"trial {trial}: n={n} d={d} k={k} chunk={chunk}"
            assert int(i1) == int(i2), ctx
            np.testing.assert_allclose(
                np.asarray(c1), np.asarray(c2), atol=1e-3, err_msg=ctx
            )
            cov1, _ = pca_ops.covariance(
                jnp.asarray(x), jnp.ones((n,), jnp.float32),
                jnp.asarray(float(n), jnp.float32),
            )
            cov2, _, nn = stream_ops.covariance_streamed(src, np.float32)
            assert nn == n, ctx
            np.testing.assert_allclose(
                np.asarray(cov1), np.asarray(cov2), atol=1e-3, err_msg=ctx
            )

    def test_reservoir_sample_uniformish(self, rng):
        from oap_mllib_tpu.ops import stream_ops

        x = np.arange(200, dtype=np.float64)[:, None]
        src = ChunkSource.from_array(x, chunk_rows=64)
        picks = stream_ops.reservoir_sample(src, 50, seed=7)
        assert picks.shape == (50, 1)
        assert len(np.unique(picks)) == 50  # sampled without replacement
        assert picks.min() >= 0 and picks.max() < 200
        # both halves represented: a biased sampler that only keeps the
        # head or tail fails this
        assert (picks < 100).any() and (picks >= 100).any()


class TestStreamedEstimators:
    def test_kmeans_streamed_recovers_blobs(self, rng):
        k, d = 4, 6
        protos = rng.normal(size=(k, d)) * 8.0
        x = (protos[rng.integers(k, size=2000)]
             + rng.normal(size=(2000, d)) * 0.05).astype(np.float32)
        src = ChunkSource.from_array(x, chunk_rows=512)
        m = KMeans(k=k, max_iter=30, seed=3).fit(src)
        assert m.summary.accelerated
        assert getattr(m.summary, "streamed", False)
        # every blob center recovered
        got = m.cluster_centers_
        for p in protos:
            assert np.min(np.linalg.norm(got - p, axis=1)) < 0.5
        # cost comparable to the in-memory fit (RNG-sensitive init: compare
        # cost, not centers — survey §7.3)
        m2 = KMeans(k=k, max_iter=30, seed=3).fit(x)
        assert m.summary.training_cost <= m2.summary.training_cost * 1.5 + 1e-6

    def test_kmeans_streamed_random_init(self, rng):
        x = rng.normal(size=(700, 5)).astype(np.float32)
        src = ChunkSource.from_array(x, chunk_rows=256)
        m = KMeans(k=3, max_iter=10, seed=1, init_mode="random").fit(src)
        assert m.summary.num_iter >= 1
        assert m.cluster_centers_.shape == (3, 5)
        assert np.isfinite(m.summary.training_cost)

    def test_kmeans_streamed_weighted_matches_in_memory(self, rng):
        """sample_weight streams too (array or width-1 ChunkSource): the
        streamed weighted fit matches the in-memory weighted fit at the
        ops level (same init) and recovers weighted blob structure
        end-to-end."""
        import jax.numpy as jnp

        from oap_mllib_tpu.ops import kmeans_ops, stream_ops

        x = rng.normal(size=(400, 6)).astype(np.float32)
        w = (rng.random(400) + 0.25).astype(np.float32)
        init = x[rng.choice(400, 3, replace=False)]
        c1, i1, t1, n1 = kmeans_ops.lloyd_run(
            jnp.asarray(x), jnp.asarray(w), jnp.asarray(init),
            12, jnp.asarray(1e-6, jnp.float32),
        )
        src = ChunkSource.from_array(x, chunk_rows=128)
        wsrc = ChunkSource.from_array(w.reshape(-1, 1), chunk_rows=128)
        c2, i2, t2, n2 = stream_ops.lloyd_run_streamed(
            src, init, 12, 1e-6, np.float32, weights=wsrc
        )
        assert int(i1) == int(i2)
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), atol=1e-4)
        np.testing.assert_allclose(float(t1), float(t2), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(n1), np.asarray(n2), atol=1e-4)

        # estimator path: weighted streamed vs weighted in-memory (k-means||
        # init RNG differs — cost-based compare, survey §7.3)
        m1 = KMeans(k=3, max_iter=20, seed=5).fit(src, sample_weight=w)
        assert getattr(m1.summary, "streamed", False)
        m2 = KMeans(k=3, max_iter=20, seed=5).fit(x, sample_weight=w)
        assert m1.summary.training_cost <= m2.summary.training_cost * 1.5 + 1e-6

    def test_kmeans_streamed_weight_mismatch_raises(self, rng):
        src = ChunkSource.from_array(rng.normal(size=(50, 3)), chunk_rows=16)
        bad = ChunkSource.from_array(np.ones((49, 1)), chunk_rows=16)
        with pytest.raises(ValueError, match="rows"):
            KMeans(k=2).fit(src, sample_weight=bad)
        bad_chunk = ChunkSource.from_array(np.ones((50, 1)), chunk_rows=8)
        with pytest.raises(ValueError, match="chunk_rows"):
            KMeans(k=2).fit(src, sample_weight=bad_chunk)

    def test_kmeans_streamed_fallback_materializes(self, rng):
        set_config(device="cpu")
        x = rng.normal(size=(200, 4))
        src = ChunkSource.from_array(x, chunk_rows=64)
        m = KMeans(k=2, seed=0).fit(src)
        assert not m.summary.accelerated
        m2 = KMeans(k=2, seed=0).fit(x)
        np.testing.assert_allclose(
            m.summary.training_cost, m2.summary.training_cost, rtol=1e-6
        )

    def test_pca_streamed_matches_in_memory(self, rng):
        x = (rng.normal(size=(800, 10)) * rng.gamma(2.0, size=10)
             + 5.0).astype(np.float32)
        src = ChunkSource.from_array(x, chunk_rows=256)
        m1 = PCA(k=4).fit(src)
        m2 = PCA(k=4).fit(x)
        assert m1.summary["streamed"] and m1.summary["n_rows"] == 800
        # sign-insensitive component compare (reference
        # IntelPCASuite.scala:80-86 pattern)
        np.testing.assert_allclose(
            np.abs(m1.components_), np.abs(m2.components_), atol=1e-4
        )
        np.testing.assert_allclose(
            m1.explained_variance_, m2.explained_variance_, atol=1e-5
        )

    def test_pca_streamed_fallback_materializes(self, rng):
        set_config(device="cpu")
        x = rng.normal(size=(300, 6))
        src = ChunkSource.from_array(x, chunk_rows=100)
        m = PCA(k=2).fit(src)
        assert not m.summary["accelerated"]
        m2 = PCA(k=2).fit(x)
        np.testing.assert_allclose(
            np.abs(m.components_), np.abs(m2.components_), atol=1e-8
        )

    def test_streamed_scoring(self, rng):
        """predict/compute_cost/transform accept a ChunkSource and match
        the in-memory scores."""
        x = rng.normal(size=(500, 8)).astype(np.float32)
        src = ChunkSource.from_array(x, chunk_rows=128)
        km = KMeans(k=3, max_iter=10, seed=2).fit(x)
        np.testing.assert_array_equal(km.predict(src), km.predict(x))
        np.testing.assert_allclose(
            km.compute_cost(src), km.compute_cost(x), rtol=1e-5
        )
        pm = PCA(k=2).fit(x)
        np.testing.assert_allclose(
            pm.transform(src), pm.transform(x), atol=1e-5
        )

    def test_pca_streamed_from_csv(self):
        path = os.path.join(DATA, "pca_data.csv")
        src = ChunkSource.from_csv(path, chunk_rows=8)
        m = PCA(k=3).fit(src)
        from oap_mllib_tpu.data.io import read_csv

        m2 = PCA(k=3).fit(read_csv(path))
        np.testing.assert_allclose(
            np.abs(m.components_), np.abs(m2.components_), atol=1e-4
        )


class TestDiskBackedSources:
    """mmap'd .npy + parquet piece readers and the spill writer
    (ISSUE 12): beyond-host-RAM tables stream end-to-end from disk
    through the same prefetch pipeline, bit-identical to memory-backed
    sources of the same rows."""

    def test_from_npy_round_trip_and_backing(self, rng, tmp_path):
        x = rng.normal(size=(700, 5)).astype(np.float32)
        path = str(tmp_path / "x.npy")
        np.save(path, x)
        src = ChunkSource.from_npy(path, chunk_rows=128)
        assert src.backing == "disk"
        assert src.n_rows == 700 and src.n_features == 5
        np.testing.assert_allclose(src.to_array(), x)

    def test_from_npy_rejects_non_2d(self, tmp_path):
        path = str(tmp_path / "v.npy")
        np.save(path, np.arange(5.0))
        with pytest.raises(ValueError, match="2-D"):
            ChunkSource.from_npy(path)

    def test_npy_reads_fire_disk_read_site(self, rng, tmp_path):
        from oap_mllib_tpu.config import set_config as _set
        from oap_mllib_tpu.utils import faults

        x = rng.normal(size=(300, 4)).astype(np.float32)
        path = str(tmp_path / "x.npy")
        np.save(path, x)
        _set(fault_spec="disk.read:err=1")
        faults.reset()
        src = ChunkSource.from_npy(path, chunk_rows=128)
        with pytest.raises(faults.InjectedPermanentError):
            src.to_array()
        _set(fault_spec="")
        faults.reset()

    def test_from_parquet_round_trip(self, rng, tmp_path):
        pa = pytest.importorskip("pyarrow")
        pq = pytest.importorskip("pyarrow.parquet")
        x = rng.normal(size=(500, 3))
        table = pa.table({f"c{j}": x[:, j] for j in range(3)})
        path = str(tmp_path / "x.parquet")
        pq.write_table(table, path, row_group_size=150)
        src = ChunkSource.from_parquet(path, chunk_rows=128)
        assert src.backing == "disk"
        assert src.n_rows == 500 and src.n_features == 3
        np.testing.assert_allclose(src.to_array(), x)

    def test_from_parquet_column_subset(self, rng, tmp_path):
        pa = pytest.importorskip("pyarrow")
        pq = pytest.importorskip("pyarrow.parquet")
        x = rng.normal(size=(100, 4))
        table = pa.table({f"c{j}": x[:, j] for j in range(4)})
        path = str(tmp_path / "x.parquet")
        pq.write_table(table, path)
        src = ChunkSource.from_parquet(
            path, chunk_rows=64, columns=["c2", "c0"]
        )
        np.testing.assert_allclose(src.to_array(), x[:, [2, 0]])

    def test_spill_round_trip_preserves_chunking(self, rng, tmp_path):
        from oap_mllib_tpu.config import set_config as _set

        _set(spill_dir=str(tmp_path))
        x = rng.normal(size=(600, 6)).astype(np.float32)
        src = ChunkSource.from_array(x, chunk_rows=128)
        spilled = src.spill_to_disk()
        assert spilled.backing == "spill"
        assert spilled.chunk_rows == src.chunk_rows
        assert spilled.n_rows == 600
        np.testing.assert_array_equal(spilled.to_array(), x)
        _set(spill_dir="")

    def test_spill_creates_a_missing_spill_dir(self, rng, tmp_path):
        """A configured spill_dir that does not exist yet is created on
        first spill — the rung must not fail with ENOENT exactly when
        it is needed (caught by the round-14 verification drive)."""
        from oap_mllib_tpu.config import set_config as _set

        fresh = str(tmp_path / "not" / "yet" / "there")
        _set(spill_dir=fresh)
        x = rng.normal(size=(100, 3)).astype(np.float32)
        spilled = ChunkSource.from_array(x, chunk_rows=64).spill_to_disk()
        np.testing.assert_array_equal(spilled.to_array(), x)
        assert os.path.isdir(fresh)
        _set(spill_dir="")

    def test_spill_reads_fire_spill_read_site(self, rng, tmp_path):
        from oap_mllib_tpu.config import set_config as _set
        from oap_mllib_tpu.utils import faults

        _set(spill_dir=str(tmp_path))
        x = rng.normal(size=(200, 4)).astype(np.float32)
        spilled = ChunkSource.from_array(x, chunk_rows=64).spill_to_disk()
        _set(fault_spec="spill.read:err=1")
        faults.reset()
        with pytest.raises(faults.InjectedPermanentError):
            spilled.to_array()
        _set(fault_spec="", spill_dir="")
        faults.reset()

    def test_spill_writer_atomic_on_failure(self, rng, tmp_path):
        """A spill that faults mid-write leaves NO committed file at the
        target path — only an ignorable tmp stream (the checkpoint
        torn-write contract, data/io.SpillWriter)."""
        from oap_mllib_tpu.config import set_config as _set
        from oap_mllib_tpu.data.io import SpillWriter
        from oap_mllib_tpu.utils import faults

        path = str(tmp_path / "spill.npy")
        _set(fault_spec="spill.write:fail=2")
        faults.reset()
        x = rng.normal(size=(100, 3)).astype(np.float32)
        with pytest.raises(faults.InjectedTransientError):
            with SpillWriter(path, 3) as w:
                w.write(x)
        assert not os.path.exists(path)
        _set(fault_spec="")
        faults.reset()

    def test_spill_writer_unknown_rows_upfront(self, rng, tmp_path):
        """File sources discover their length on the walk: the writer
        streams raw data and stamps the header at commit."""
        from oap_mllib_tpu.data.io import SpillWriter

        path = str(tmp_path / "s.npy")
        x = rng.normal(size=(137, 4)).astype(np.float32)
        with SpillWriter(path, 4) as w:
            for lo in range(0, 137, 50):
                w.write(x[lo: lo + 50])
        back = np.load(path)
        np.testing.assert_array_equal(back, x)

    def test_kmeans_disk_streamed_bit_identical_to_memory_streamed(
        self, rng, tmp_path
    ):
        """The acceptance leg: a disk-backed fit is BIT-identical to the
        same streamed fit from memory (same rows, chunking, init RNG)."""
        x = rng.normal(size=(900, 6)).astype(np.float32)
        path = str(tmp_path / "x.npy")
        np.save(path, x)
        m_mem = KMeans(k=3, seed=5, max_iter=6).fit(
            ChunkSource.from_array(x, chunk_rows=256)
        )
        m_disk = KMeans(k=3, seed=5, max_iter=6).fit(
            ChunkSource.from_npy(path, chunk_rows=256)
        )
        np.testing.assert_array_equal(
            m_disk.cluster_centers_, m_mem.cluster_centers_
        )
        assert m_disk.summary.route["route"] == "streamed"

    def test_pca_parquet_streamed_matches_in_memory(self, rng, tmp_path):
        pa = pytest.importorskip("pyarrow")
        pq = pytest.importorskip("pyarrow.parquet")
        x = rng.normal(size=(400, 6))
        table = pa.table({f"c{j}": x[:, j] for j in range(6)})
        path = str(tmp_path / "x.parquet")
        pq.write_table(table, path, row_group_size=100)
        m_disk = PCA(k=2).fit(ChunkSource.from_parquet(path, chunk_rows=128))
        m_mem = PCA(k=2).fit(x)
        # f64 parquet columns stage as f32 chunks on the streamed route;
        # the in-memory fit sees the f64 rows cast once — 1e-5 is the
        # cross-route contract (disk-vs-memory STREAMED is bit-exact,
        # pinned by the K-Means/ALS legs above)
        np.testing.assert_allclose(
            np.abs(m_disk.components_), np.abs(m_mem.components_),
            atol=1e-5,
        )
        np.testing.assert_allclose(
            m_disk.explained_variance_, m_mem.explained_variance_,
            atol=1e-5,
        )

    def test_als_disk_triples_match_memory_streamed(self, rng, tmp_path):
        from oap_mllib_tpu.models.als import ALS

        u = rng.integers(30, size=400).astype(np.float64)
        i = rng.integers(20, size=400).astype(np.float64)
        r = rng.random(400)
        tri = np.stack([u, i, r], axis=1)
        path = str(tmp_path / "tri.npy")
        np.save(path, tri)
        m_mem = ALS(rank=3, max_iter=2, seed=3).fit(
            ChunkSource.from_array(tri, chunk_rows=128)
        )
        m_disk = ALS(rank=3, max_iter=2, seed=3).fit(
            ChunkSource.from_npy(path, chunk_rows=128)
        )
        np.testing.assert_array_equal(
            m_disk.user_factors_, m_mem.user_factors_
        )
        np.testing.assert_array_equal(
            m_disk.item_factors_, m_mem.item_factors_
        )
