"""Live-world recovery plane units (ISSUE 10, utils/recovery.py +
utils/faults.py chaos/kill): collective deadlines, the crash-record
sideband, coordinated abort, the chaos schedule, and the supervised
ladder stamp — everything the 2-process drills exercise end to end,
proven here with stubbed worlds so the logic is asserted even on hosts
that cannot form multiprocess jax worlds."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.utils import faults, recovery

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestConfigSurface:
    def test_negative_collective_timeout_raises(self):
        set_config(collective_timeout=-1.0)
        with pytest.raises(ValueError, match="collective_timeout"):
            recovery.collective_timeout_cfg()

    def test_negative_timeout_raises_at_dispatch_even_single_process(self):
        """The kmeans_kernel/fault_spec contract: a nonsense knob must
        raise at the seam, not silently disarm."""
        set_config(collective_timeout=-2.0)
        with pytest.raises(ValueError, match="collective_timeout"):
            recovery.guarded_dispatch("psum", "data", lambda: 1)

    def test_zero_is_disarmed_passthrough(self):
        set_config(collective_timeout=0.0)
        assert recovery.guarded_dispatch("psum", "data", lambda: 41) == 41

    def test_chaos_typo_raises_at_first_site_call(self):
        set_config(chaos="not-a-spec")
        with pytest.raises(ValueError, match="seed:rate"):
            faults.maybe_fault("stream.read")


class TestChaosSchedule:
    def test_parse_grammar(self):
        st = faults.parse_chaos("7:0.25:fail+kill:3")
        assert (st.seed, st.rate, st.kinds, st.budget) == (
            7, 0.25, ["fail", "kill"], 3
        )
        assert faults.parse_chaos("") is None
        assert faults.parse_chaos("5:0.5").kinds == ["fail"]
        assert faults.parse_chaos("5:0.5:oom:*").budget == -1

    @pytest.mark.parametrize("bad", [
        "x:0.1", "7:nope", "7:1.5", "7:-0.1", "7:0.1:boom",
        "7:0.1:fail:-1", "7", "7:0.1:fail:3:extra",
    ])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            faults.parse_chaos(bad)

    def test_decision_is_deterministic_and_rank_dependent(self):
        st = faults.parse_chaos("11:0.5")
        seq0 = [st.decide("stream.read", c, 0) for c in range(64)]
        assert seq0 == [st.decide("stream.read", c, 0) for c in range(64)]
        seq1 = [st.decide("stream.read", c, 1) for c in range(64)]
        # ranks see INDEPENDENT schedules — the one-rank-killed,
        # peers-survive drill depends on it
        assert seq0 != seq1
        assert any(seq0) and not all(seq0)

    def test_budget_caps_total_fires(self):
        set_config(chaos="3:1.0:fail:2")
        fired = 0
        for _ in range(6):
            try:
                faults.maybe_fault("stream.read")
            except faults.InjectedTransientError:
                fired += 1
        assert fired == 2

    def test_kinds_cycle_deterministically(self):
        set_config(chaos="3:1.0:fail+oom")
        with pytest.raises(faults.InjectedTransientError):
            faults.maybe_fault("stream.read")
        with pytest.raises(faults.InjectedOOMError):
            faults.maybe_fault("stream.read")
        with pytest.raises(faults.InjectedTransientError):
            faults.maybe_fault("stream.read")

    def test_chaos_layers_on_top_of_explicit_spec(self):
        set_config(fault_spec="stream.read:err=1", chaos="3:1.0:fail:1")
        with pytest.raises(faults.InjectedPermanentError):
            faults.maybe_fault("stream.read")  # explicit spec wins first
        with pytest.raises(faults.InjectedTransientError):
            faults.maybe_fault("stream.read")  # then the chaos schedule
        faults.maybe_fault("stream.read")  # both budgets spent

    def test_stats_expose_chaos_counters(self):
        # the registry is process-global and re-arms on spec CHANGE, so
        # each test uses a unique spec string (fresh counters)
        set_config(chaos="31:1.0:fail:1")
        with pytest.raises(faults.InjectedTransientError):
            faults.maybe_fault("prefetch.stage")
        st = faults.stats()["chaos"]
        assert st["fired"] == 1 and st["calls"] == {"prefetch.stage": 1}

    def test_rearms_on_spec_change(self):
        set_config(chaos="32:1.0:fail:1")
        with pytest.raises(faults.InjectedTransientError):
            faults.maybe_fault("stream.read")
        faults.maybe_fault("stream.read")  # budget spent
        set_config(chaos="33:1.0:fail:1")  # new spec -> fresh budget
        with pytest.raises(faults.InjectedTransientError):
            faults.maybe_fault("stream.read")

    def test_kill_kind_sigkills_the_process(self, tmp_path):
        """``kill`` is a real SIGKILL (a preemption), not an exception —
        proven in a subprocess; the fault_spec grammar accepts it too."""
        proc = subprocess.run(
            [sys.executable, "-c",
             "from oap_mllib_tpu.utils import faults\n"
             "faults.maybe_fault('stream.read')\n"
             "print('SURVIVED')"],
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "OAP_MLLIB_TPU_FAULT_SPEC": "stream.read:kill=1",
                 "PYTHONPATH": _REPO},
            capture_output=True, text=True, timeout=120, cwd=_REPO,
        )
        assert proc.returncode == -9, proc.stdout + proc.stderr
        assert "SURVIVED" not in proc.stdout


class TestCollectiveDispatchSite:
    def test_site_is_registered(self):
        assert "collective.dispatch" in faults.SITES

    def test_facade_dispatch_is_injectable(self, rng):
        """The satellite: faults.maybe_fault threads through the eager
        collective facade, so the recovery drills can fault the exact
        seam where a dead peer would surface."""
        from oap_mllib_tpu.parallel import collective
        from oap_mllib_tpu.parallel.mesh import get_mesh

        import jax.numpy as jnp

        mesh = get_mesh()
        x = jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32))
        set_config(fault_spec="collective.dispatch:fail=1")
        with pytest.raises(faults.InjectedTransientError):
            collective.allreduce_sum(x, mesh)
        set_config(fault_spec="")
        # healthy dispatch: each device's (1, 4) shard sums to the
        # replicated (1, 4) result
        out = collective.allreduce_sum(x, mesh)
        np.testing.assert_allclose(
            np.asarray(out)[0], np.asarray(x).sum(axis=0), rtol=1e-5
        )


def _two_process(monkeypatch, rank=0):
    monkeypatch.setattr(recovery, "_world", lambda: 2)
    monkeypatch.setattr(recovery, "_rank", lambda: rank)


class TestWatchdog:
    def test_fast_dispatch_passes_through_and_fingerprints(self, monkeypatch):
        _two_process(monkeypatch)
        set_config(collective_timeout=5.0)
        before = recovery.last_completed()["count"]
        assert recovery.guarded_dispatch("psum", "data", lambda: 7) == 7
        after = recovery.last_completed()
        assert after["count"] == before + 1
        assert after["last"] == "psum|data"

    def test_worker_exception_propagates(self, monkeypatch):
        _two_process(monkeypatch)
        set_config(collective_timeout=5.0)

        def boom():
            raise RuntimeError("inner failure")

        with pytest.raises(RuntimeError, match="inner failure"):
            recovery.guarded_dispatch("psum", "data", boom)

    def test_timeout_raises_named_diagnosis(self, monkeypatch, tmp_path):
        _two_process(monkeypatch)
        crash = str(tmp_path / "sideband")
        set_config(collective_timeout=0.3, crash_dir=crash)
        t0 = time.monotonic()
        with pytest.raises(recovery.CollectiveTimeoutError) as ei:
            recovery.guarded_dispatch(
                "allreduce_sum", "data", lambda: time.sleep(3)
            )
        assert time.monotonic() - t0 < 2.0
        e = ei.value
        assert e.op == "allreduce_sum" and e.axis == "data"
        assert e.elapsed_s >= 0.3
        msg = str(e)
        assert "allreduce_sum" in msg and "collective_timeout=0.3" in msg
        assert "Recovery:" in msg  # the runbook pointer
        # the survivor's crash record landed in the sideband
        rec = json.load(open(recovery.crash_record_path(crash, 0)))
        assert rec["fault_class"] == recovery.FAULT_TIMEOUT
        assert rec["op"] == "allreduce_sum"

    def test_timeout_metrics_counted(self, monkeypatch):
        from oap_mllib_tpu.telemetry import metrics as tm

        _two_process(monkeypatch)
        set_config(collective_timeout=0.2)
        before = tm.counter(
            "oap_recovery_timeouts_total", {"op": "psum"}).value
        with pytest.raises(recovery.CollectiveTimeoutError):
            recovery.guarded_dispatch("psum", "data", lambda: time.sleep(2))
        assert tm.counter(
            "oap_recovery_timeouts_total", {"op": "psum"}
        ).value == before + 1

    def test_peer_poison_aborts_promptly(self, monkeypatch, tmp_path):
        """A peer's crash record must beat the deadline by a wide margin:
        the whole point of the sideband is not burning the full timeout
        when the fault is already diagnosed."""
        _two_process(monkeypatch)
        crash = str(tmp_path / "sideband")
        os.makedirs(crash)
        with open(recovery.crash_record_path(crash, 1), "w") as f:
            json.dump({"rank": 1, "fault_class": "oom", "site": "als.fit",
                       "error": "boom", "last_checkpoint_step": 5}, f)
        set_config(collective_timeout=30.0, crash_dir=crash)
        t0 = time.monotonic()
        with pytest.raises(recovery.PeerAbortError) as ei:
            recovery.guarded_dispatch(
                "process_allgather", "host", lambda: time.sleep(30)
            )
        assert time.monotonic() - t0 < 5.0  # nowhere near the 30s deadline
        assert ei.value.record["rank"] == 1
        msg = str(ei.value)
        assert "rank 1" in msg and "oom" in msg and "als.fit" in msg
        assert "checkpoint step was 5" in msg
        # the victim wrote its own record too (machine-readable on EVERY rank)
        rec = json.load(open(recovery.crash_record_path(crash, 0)))
        assert rec["fault_class"] == recovery.FAULT_PEER_ABORT

    def test_single_process_never_watches(self):
        """world==1: armed or not, the dispatch runs inline (there is no
        peer to wait for)."""
        set_config(collective_timeout=0.05)
        t0 = time.monotonic()
        assert recovery.guarded_dispatch(
            "psum", "data", lambda: (time.sleep(0.2), 9)[1]
        ) == 9
        assert time.monotonic() - t0 >= 0.2  # ran to completion, no timeout


class TestCrashRecords:
    def test_disarmed_is_noop(self, tmp_path):
        set_config(crash_dir="")
        assert recovery.write_crash_record("s", "oom", "x") is None

    def test_record_schema(self, tmp_path):
        crash = str(tmp_path / "sideband")
        set_config(crash_dir=crash)
        path = recovery.write_crash_record(
            "kmeans.fit", "transient", "connection reset", op="psum",
            elapsed_s=1.25,
        )
        rec = json.load(open(path))
        assert rec["version"] == recovery.CRASH_RECORD_VERSION
        assert rec["rank"] == 0 and rec["world"] >= 1
        assert rec["site"] == "kmeans.fit"
        assert rec["fault_class"] == "transient"
        assert rec["op"] == "psum" and rec["elapsed_s"] == 1.25
        # the durable-step tracker is process-global, so earlier
        # checkpoint tests in a full-suite run may have advanced it —
        # only its presence and type are this test's contract
        assert isinstance(rec["last_checkpoint_step"], int)
        assert rec["last_checkpoint_step"] >= -1
        assert isinstance(rec["telemetry"], dict)
        assert "last_completed" in rec

    def test_record_carries_last_durable_checkpoint_step(self, tmp_path):
        from oap_mllib_tpu.utils import checkpoint as ckpt

        crash = str(tmp_path / "sideband")
        set_config(crash_dir=crash)
        prev = ckpt._LAST_DURABLE["step"]
        try:
            ckpt._note_durable(7)
            path = recovery.write_crash_record("s", "oom", "x")
            assert json.load(open(path))["last_checkpoint_step"] >= 7
        finally:
            with ckpt._durable_lock:
                ckpt._LAST_DURABLE["step"] = prev

    def test_check_poison_ignores_self_and_parses_peers(self, tmp_path):
        d = str(tmp_path)
        with open(recovery.crash_record_path(d, 0), "w") as f:
            json.dump({"rank": 0, "fault_class": "oom"}, f)
        assert recovery.check_poison(d, 0) is None  # own record ignored
        with open(recovery.crash_record_path(d, 2), "w") as f:
            json.dump({"rank": 2, "fault_class": "killed"}, f)
        assert recovery.check_poison(d, 0)["rank"] == 2

    def test_torn_record_still_poisons(self, tmp_path):
        d = str(tmp_path)
        with open(recovery.crash_record_path(d, 1), "w") as f:
            f.write("{not json")
        rec = recovery.check_poison(d, 0)
        assert rec == {"rank": 1}  # a half-dead peer is still dead

    def test_clear_crash_records(self, tmp_path):
        d = str(tmp_path)
        for r in (0, 1):
            with open(recovery.crash_record_path(d, r), "w") as f:
                json.dump({"rank": r}, f)
        assert recovery.clear_crash_records(d) == 2
        assert recovery.check_poison(d, 99) is None


class TestSupervisedLadder:
    def _fit(self, monkeypatch, world, crash_dir, fn=lambda d: "ok"):
        from oap_mllib_tpu.utils import resilience

        monkeypatch.setattr(resilience, "_world", lambda: world)
        if world > 1:
            monkeypatch.setattr(recovery, "_world", lambda: world)
        set_config(crash_dir=crash_dir)
        stats = resilience.ResilienceStats()
        out = resilience.resilient_fit("kmeans", fn, None, stats=stats)
        return out, stats

    def test_multiprocess_without_sideband_stays_bypassed(self, monkeypatch):
        _, stats = self._fit(monkeypatch, 2, "")
        assert stats.ladder == "bypassed(static-world)"

    def test_multiprocess_with_sideband_is_supervised(self, monkeypatch,
                                                      tmp_path):
        _, stats = self._fit(monkeypatch, 2, str(tmp_path / "sb"))
        assert stats.ladder == "supervised"

    def test_single_process_stays_active(self, monkeypatch, tmp_path):
        _, stats = self._fit(monkeypatch, 1, str(tmp_path / "sb"))
        assert stats.ladder == "active"

    def test_fatal_fault_poisons_and_propagates_unchanged(self, monkeypatch,
                                                          tmp_path):
        crash = str(tmp_path / "sb")

        def boom(degraded):
            raise MemoryError("RESOURCE_EXHAUSTED: drill")

        with pytest.raises(MemoryError, match="drill"):
            self._fit(monkeypatch, 2, crash, boom)
        rec = json.load(open(recovery.crash_record_path(crash, 0)))
        assert rec["site"] == "kmeans.fit"
        assert rec["fault_class"] == "oom"

    def test_recovery_errors_do_not_double_record(self, monkeypatch,
                                                  tmp_path):
        """A CollectiveTimeoutError reaching resilient_fit was already
        recorded at the dispatch seam — record_fatal must not overwrite
        the precise record with a generic one."""
        crash = str(tmp_path / "sb")

        def boom(degraded):
            raise recovery.CollectiveTimeoutError("already recorded")

        with pytest.raises(recovery.CollectiveTimeoutError):
            self._fit(monkeypatch, 2, crash, boom)
        assert not os.path.exists(recovery.crash_record_path(crash, 0))
