"""Capability-weighted sharding units (ISSUE 15): probe determinism +
pin grammar, planner properties, balanced source views (live re-plan,
weight lockstep, resilience re-chunk), the straggler controller, block
offsets, and the summary/fleet exposure."""

import json

import numpy as np
import pytest

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.models.kmeans import KMeans
from oap_mllib_tpu.parallel import balance
from oap_mllib_tpu.telemetry import fleet
from oap_mllib_tpu.utils import dispatch


@pytest.fixture(autouse=True)
def _clean():
    balance._reset_for_tests()
    fleet._reset_for_tests()
    yield
    balance._reset_for_tests()
    fleet._reset_for_tests()


def _capworld(*caps, hbm=0, host=0):
    return balance.fold_world(np.asarray(
        [[c, 1.0, hbm, host] for c in caps], np.float64
    ))


F = len(fleet.FRAME_FIELDS)


def _frames(walls, rows=None):
    out = np.ones((len(walls), F), np.float64)
    out[:, 0] = walls
    if rows is not None:
        out[:, fleet.FRAME_FIELDS.index("rows")] = rows
    return out


class TestKnobs:
    def test_capability_sharding_modes(self):
        assert balance.armed(1) is False  # auto, single process
        assert balance.armed(2) is True
        set_config(capability_sharding="on")
        assert balance.armed(1) is True
        set_config(capability_sharding="off")
        assert balance.armed(8) is False

    def test_capability_sharding_typo_raises(self):
        set_config(capability_sharding="onn")
        with pytest.raises(ValueError, match="capability_sharding"):
            balance.armed(2)

    def test_rebalance_threshold_validates(self):
        set_config(rebalance_threshold=1.0)
        with pytest.raises(ValueError, match="rebalance_threshold"):
            balance.rebalance_threshold_cfg()

    def test_rebalance_patience_validates(self):
        set_config(rebalance_patience=0)
        with pytest.raises(ValueError, match="rebalance_patience"):
            balance.rebalance_patience_cfg()


class TestProbe:
    def test_probe_deterministic_cached(self):
        a = dispatch.throughput_probe(0)
        b = dispatch.throughput_probe(0)
        assert a == b  # cached per process
        assert a > 0

    def test_pinned_bare_float(self):
        set_config(rank_capability="0.25")
        assert dispatch.pinned_capability() == 0.25

    def test_pinned_map_covers_this_rank(self):
        set_config(rank_capability="0:0.75,1:0.25")
        # the suite runs as process_index 0
        assert dispatch.pinned_capability() == 0.75

    def test_pinned_map_missing_rank_falls_back_to_probe(self):
        set_config(rank_capability="7:0.25")
        assert dispatch.pinned_capability() is None
        cap, origin = dispatch.rank_capability()
        assert origin == "probe" and cap > 0

    def test_pinned_typo_raises(self):
        set_config(rank_capability="fast")
        with pytest.raises(ValueError, match="rank_capability"):
            dispatch.pinned_capability()

    def test_pinned_nonpositive_raises(self):
        set_config(rank_capability="0")
        with pytest.raises(ValueError, match="> 0"):
            dispatch.pinned_capability()

    def test_rank_capability_origin_pinned(self):
        set_config(rank_capability="2.0")
        assert dispatch.rank_capability() == (2.0, "pinned")


class TestFoldWorld:
    def test_normalizes_to_mean_one(self):
        cw = _capworld(2.0, 1.0, 1.0)
        assert cw.weights.mean() == pytest.approx(1.0)
        assert cw.weights[0] == pytest.approx(1.5)
        assert cw.origin == "pinned"

    def test_rejects_wrong_shape(self):
        with pytest.raises(ValueError, match="capability frame"):
            balance.fold_world(np.zeros((2, 3)))

    def test_mixed_origins(self):
        cw = balance.fold_world(
            np.asarray([[1.0, 1, 0, 0], [1.0, 0, 0, 0]])
        )
        assert cw.origin == "mixed"


class TestPlanExtents:
    def test_sum_to_n_and_quantized(self):
        ext, over = balance.plan_extents(1000, 100, [1.0, 0.25])
        assert not over
        assert sum(r for _, r in ext) == 1000
        assert ext[0] == (0, 800) and ext[1] == (800, 200)

    def test_world_one_degenerates_to_equal(self):
        ext, over = balance.plan_extents(12345, 256, [3.7])
        assert ext == [(0, 12345)] and not over

    def test_equal_weights_equal_chunks(self):
        ext, _ = balance.plan_extents(4096, 256, [1.0, 1.0])
        assert ext[0][1] == ext[1][1] == 2048

    def test_caps_respected_with_redistribution(self):
        ext, over = balance.plan_extents(
            1000, 100, [1.0, 1.0, 1.0], caps_rows=[200, 0, 0]
        )
        assert not over
        assert ext[0][1] == 200  # capped rank saturates
        assert sum(r for _, r in ext) == 1000

    def test_infeasible_caps_overflow_loudly(self):
        ext, over = balance.plan_extents(
            1000, 100, [1.0, 1.0], caps_rows=[100, 100]
        )
        assert over
        assert sum(r for _, r in ext) == 1000

    def test_world_one_over_cap_flag(self):
        _, over = balance.plan_extents(1000, 100, [1.0], caps_rows=[500])
        assert over

    def test_zero_weight_rank_floored_not_starved(self):
        ext, _ = balance.plan_extents(10000, 100, [1.0, 1e-12])
        assert ext[1][1] >= 0  # floor keeps the plan valid
        assert sum(r for _, r in ext) == 10000

    def test_invalid_inputs_raise(self):
        with pytest.raises(ValueError):
            balance.plan_extents(0, 100, [1.0])
        with pytest.raises(ValueError):
            balance.plan_extents(100, 0, [1.0])


class TestBlockOffsets:
    def test_deadband_keeps_uniform(self):
        assert balance.plan_block_offsets(1000, [1.0, 1.02]) is None
        assert balance.plan_block_offsets(1000, [1.0]) is None

    def test_weighted_offsets_monotone_nonempty(self):
        off = balance.plan_block_offsets(1000, [1.0, 0.25, 0.25])
        assert off is not None
        assert off[0] == 0 and off[-1] == 1000
        assert all(np.diff(off) >= 1)
        assert off[1] - off[0] > off[2] - off[1]  # fast rank, bigger block

    def test_block_offsets_disarmed_returns_none(self):
        set_config(capability_sharding="off")
        assert balance.block_offsets(1000, 4) is None

    def test_block_offsets_with_injected_capworld(self):
        cw = _capworld(1.0, 0.25)
        off = balance.block_offsets(1000, 2, capworld=cw)
        assert off is not None and off[1] == 800

    def test_block_offsets_irregular_slots_keep_uniform(self):
        cw = _capworld(1.0, 0.25)
        assert balance.block_offsets(1000, 3, capworld=cw) is None

    def test_block_offsets_hbm_priced(self):
        # fast rank with tiny HBM: its key share caps at the budget
        frames = np.asarray([
            [4.0, 1.0, 10_000, 0],  # fast, 10 KB HBM
            [1.0, 1.0, 0, 0],  # slow, unbounded
        ])
        cw = balance.fold_world(frames)
        off = balance.block_offsets(10000, 2, bytes_per_key=100,
                                    capworld=cw)
        assert off is not None
        # cap = 10_000 * fraction / 100 = 25 keys for rank 0
        assert off[1] - off[0] <= 25 + 1


class TestHostCaps:
    def test_disk_backed_uncapped(self):
        cw = _capworld(1.0, 1.0, host=1 << 20)
        assert balance.host_caps_rows(cw, 100, "disk") is None
        assert balance.host_caps_rows(cw, 0, "memory") is None

    def test_memory_backed_capped_by_host_budget(self):
        cw = _capworld(1.0, 1.0, host=1 << 20)
        caps = balance.host_caps_rows(cw, 1024, "memory")
        assert caps is not None
        assert caps[0] == int((1 << 20) * balance._HOST_FRACTION / 1024)


class TestBalancedView:
    def test_identity_plan_matches_plain_source(self):
        x = np.arange(1000 * 3, dtype=np.float32).reshape(1000, 3)
        set_config(capability_sharding="off")
        src = balance.local_sources(x, chunk_rows=128)
        plain = ChunkSource.from_array(x, chunk_rows=128)
        got = [(c.copy(), v) for c, v in src]
        want = [(c.copy(), v) for c, v in plain]
        assert len(got) == len(want)
        for (cg, vg), (cw_, vw) in zip(got, want):
            assert vg == vw
            np.testing.assert_array_equal(cg, cw_)
        assert isinstance(src, ChunkSource)  # models route it streamed

    def test_extents_partition_rows_across_ranks(self):
        x = np.arange(1000 * 2, dtype=np.float32).reshape(1000, 2)
        cw = _capworld(1.0, 0.25)
        set_config(capability_sharding="on")
        plan = balance.make_plan(1000, 128, world=2, capworld=cw)
        v0 = balance.BalancedView(x, plan, 128, rank=0)
        v1 = balance.BalancedView(x, plan, 128, rank=1)
        rows = np.concatenate([v0.to_array(), v1.to_array()])
        np.testing.assert_array_equal(rows, x)
        assert v0.n_rows > v1.n_rows

    def test_replan_takes_effect_next_pass(self):
        x = np.zeros((1024, 2), np.float32)
        cw = _capworld(1.0, 1.0)
        set_config(capability_sharding="on")
        plan = balance.make_plan(1024, 128, world=2, capworld=cw)
        v1 = balance.BalancedView(x, plan, 128, rank=1)
        assert sum(1 for _ in v1) == 4  # 512 rows / 128
        new_ext, _ = balance.plan_extents(1024, 128, [3.0, 1.0])
        plan.set_extents(new_ext, np.asarray([1.5, 0.5]))
        assert sum(1 for _ in v1) == 2  # 256 rows after the re-plan
        assert v1.n_rows == 256

    def test_weight_view_lockstep(self):
        x = np.random.default_rng(0).normal(size=(700, 4)).astype(
            np.float32)
        w = np.ones(700)
        set_config(capability_sharding="off")
        src, wsrc = balance.local_sources(x, w, chunk_rows=128)
        assert isinstance(wsrc, ChunkSource)
        assert wsrc.n_features == 1
        assert wsrc.chunk_rows == src.chunk_rows
        assert wsrc.n_rows == src.n_rows

    def test_with_chunk_rows_stays_aligned(self):
        x = np.zeros((1024, 2), np.float32)
        set_config(capability_sharding="off")
        src = balance.local_sources(x, chunk_rows=256)
        halved = src.with_chunk_rows(128)
        assert isinstance(halved, balance.BalancedView)
        assert halved.chunk_rows == 128
        assert halved.to_array().shape == (1024, 2)

    def test_mismatched_weight_length_raises(self):
        with pytest.raises(ValueError, match="sample_weight rows"):
            balance.local_sources(
                np.zeros((10, 2)), np.ones(5), chunk_rows=4
            )


class TestController:
    def _plan(self, world=2, n=30000, chunk=512):
        cw = _capworld(*([1.0] * world))
        set_config(capability_sharding="on")
        return balance.make_plan(n, chunk, world=world, capworld=cw)

    def test_replan_after_patience(self):
        set_config(rebalance_threshold=1.4, rebalance_patience=2)
        plan = self._plan()
        rows = [e[1] for e in plan.extents()]
        fr = _frames([1.0, 4.0], rows=rows)
        assert balance.observe_pass("lloyd_loop", fr) is None  # pass 1
        dec = balance.observe_pass("lloyd_loop", fr)  # pass 2 = patience
        assert dec is not None
        assert dec["slowest_rank"] == 1
        assert dec["new_extents"][1][1] < dec["old_extents"][1][1]
        assert sum(r for _, r in plan.extents()) == 30000

    def test_below_threshold_never_replans(self):
        set_config(rebalance_threshold=1.5, rebalance_patience=1)
        plan = self._plan()
        fr = _frames([1.0, 1.2], rows=[e[1] for e in plan.extents()])
        for _ in range(6):
            assert balance.observe_pass("lloyd_loop", fr) is None

    def test_falling_trend_suppresses(self):
        # patience 4 so the trend window (4 passes) is computable at
        # the would-be trigger: a steadily-shrinking skew (a cold-cache
        # relaunch warming up) must NOT trigger a re-plan
        set_config(rebalance_threshold=1.4, rebalance_patience=4)
        plan = self._plan()
        rows = [e[1] for e in plan.extents()]
        for wall in (64.0, 24.0, 10.0, 5.0, 3.5, 3.0):
            dec = balance.observe_pass(
                "lloyd_loop", _frames([1.0, wall], rows=rows)
            )
            assert dec is None

    def test_init_phase_never_replans(self):
        set_config(rebalance_threshold=1.2, rebalance_patience=1)
        plan = self._plan()
        fr = _frames([1.0, 5.0], rows=[e[1] for e in plan.extents()])
        for _ in range(4):
            assert balance.observe_pass("init_centers", fr) is None

    def test_disarmed_ignores_frames(self):
        set_config(capability_sharding="off")
        assert balance.observe_pass("lloyd_loop", _frames([1, 9])) is None

    def test_decisions_deterministic(self):
        def run():
            balance._reset_for_tests()
            set_config(rebalance_threshold=1.4, rebalance_patience=2)
            plan = self._plan()
            fr = _frames([1.0, 4.0],
                         rows=[e[1] for e in plan.extents()])
            decs = []
            for _ in range(6):
                d = balance.observe_pass("lloyd_loop", fr)
                if d:
                    decs.append(d)
            return plan.extents(), decs

        a = run()
        b = run()
        assert a == b

    def test_persistent_straggler_writes_hint(self, tmp_path):
        set_config(rebalance_threshold=1.4, rebalance_patience=1,
                   crash_dir=str(tmp_path))
        plan = self._plan()
        rows = [e[1] for e in plan.extents()]
        for _ in range(6):  # streak >= 2*patience after a replan
            balance.observe_pass(
                "lloyd_loop", _frames([1.0, 4.0], rows=rows)
            )
        hint_path = tmp_path / balance.HINT_FILENAME
        assert hint_path.exists()
        hint = json.loads(hint_path.read_text())
        assert hint["rank"] == 1
        assert hint["schema"] == 1

    def test_replan_capped_at_max(self):
        set_config(rebalance_threshold=1.1, rebalance_patience=1)
        plan = self._plan()
        for _ in range(balance._MAX_REPLANS + 10):
            balance.observe_pass(
                "lloyd_loop",
                _frames([1.0, 4.0],
                        rows=[e[1] for e in plan.extents()]),
            )
        assert len(balance.decisions()) <= balance._MAX_REPLANS


class TestSupervisorHint:
    def test_supervisor_consumes_hint(self, tmp_path):
        from oap_mllib_tpu.utils.supervisor import Supervisor

        (tmp_path / balance.HINT_FILENAME).write_text(
            json.dumps({"schema": 1, "rank": 0, "skew_ratio": 3.0,
                        "streak_passes": 4})
        )
        sup = Supervisor(
            lambda r, w, c, a: ["true"], world=1,
            crash_dir=str(tmp_path), restart_budget=0,
        )
        hint = sup._read_balance_hint()
        assert hint is not None and hint["rank"] == 0
        assert not (tmp_path / balance.HINT_FILENAME).exists()  # consumed
        assert sup._read_balance_hint() is None


class TestFitIntegration:
    def _x(self, rows=3000, d=8):
        return np.random.default_rng(0).normal(size=(rows, d)).astype(
            np.float32)

    def test_balanced_fit_lands_summary_and_span(self):
        set_config(capability_sharding="on", fleet_stats="on")
        src = balance.local_sources(self._x(), chunk_rows=300)
        m = KMeans(k=3, seed=0, init_mode="random", max_iter=3,
                   tol=0.0).fit(src)
        blk = m.summary.balance
        assert blk["enabled"] is True
        assert blk["world"] == 1
        assert blk["extents"] == [[0, 3000]]
        assert blk["origin"] in ("probe", "pinned")
        assert blk["replans"] == []
        names = [c["name"] for c in m.summary.telemetry["spans"]["children"]]
        assert "balance" in names
        # fleet exposure: assignment vs achievement
        assert m.summary.fleet["per_rank_rows"] is not None
        assert m.summary.fleet["per_rank_capability"][0] > 0

    def test_controller_state_resets_between_fits(self):
        set_config(capability_sharding="on", fleet_stats="on")
        src = balance.local_sources(self._x(), chunk_rows=300)
        KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(src)
        assert balance.decisions() == []  # finalize drained it
        m = KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(src)
        assert m.summary.balance["passes_observed"] >= 2

    def test_disarmed_fit_has_no_balance_block(self):
        set_config(capability_sharding="off")
        src = ChunkSource.from_array(self._x(), chunk_rows=300)
        m = KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(src)
        assert not hasattr(m.summary, "balance")

    def test_balanced_pca_fit(self):
        from oap_mllib_tpu.models.pca import PCA

        set_config(capability_sharding="on", fleet_stats="on")
        src = balance.local_sources(self._x(rows=2000), chunk_rows=500)
        model = PCA(k=2).fit(src)
        s = model.summary
        blk = s.get("balance") if isinstance(s, dict) else s.balance
        assert blk["extents"] == [[0, 2000]]

    def test_healthz_carries_capability_and_rows(self):
        from oap_mllib_tpu.telemetry.fleet import _healthz_payload

        set_config(capability_sharding="on", fleet_stats="on")
        src = balance.local_sources(self._x(), chunk_rows=300)
        KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(src)
        hz = _healthz_payload()
        assert "capability" in hz
        assert "rows_processed" in hz
        assert hz["capability"] > 0


class TestFrameExposure:
    def test_local_frame_carries_rows_and_capability(self):
        from oap_mllib_tpu.data.prefetch import PrefetchStats

        stats = PrefetchStats()
        stats.rows = 777
        frame = fleet.local_frame(stats, 1.0)
        named = dict(zip(fleet.FRAME_FIELDS, frame))
        assert named["rows"] == 777
        assert "capability" in named  # 0.0 when nothing probed yet
