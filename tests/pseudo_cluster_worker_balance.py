"""Capability-weighted sharding pseudo-cluster worker (ISSUE 15).

One rank of a real ``jax.distributed`` world driving the balance plane
(parallel/balance.py).  Every rank holds the SAME deterministic global
table and takes its shard through ``balance.local_sources`` — the
capability-weighted extent view.  Rank 1 is deliberately slowed: its
row slices sleep per chunk (a throttled host / cold-cache relaunch
stand-in).  Modes (env ``BALANCE_WORKER_MODE``):

- ``weighted`` — capabilities PINNED ``0:1.0,1:0.25`` → rank 1 gets a
  quarter-weight extent up front; the fit should beat the equal layout
  end-to-end (the parent compares walls).
- ``equal`` — ``capability_sharding=off`` → the equal-extent baseline
  over the identical slowed world (the parent's reference wall AND the
  parity oracle).
- ``rebalance`` — capabilities pinned EQUAL (1.0/1.0: same host, the
  probe would agree) so the initial plan is equal; the live straggler
  controller must detect the skew from the fleet rollups and re-plan
  extents mid-fit (the parent asserts a replan decision landed in
  ``summary.balance`` and rank 1's extent shrank).

Every rank prints RESULT with its fit wall, the rounded centers digest,
and the ``balance``/``fleet`` summary blocks.

Invoked as:  python pseudo_cluster_worker_balance.py RANK NPROC COORD LOCAL_DEV
"""

import json
import os
import sys
import time

rank, nproc = int(sys.argv[1]), int(sys.argv[2])
coord, local_dev = sys.argv[3], int(sys.argv[4])
mode = os.environ["BALANCE_WORKER_MODE"]
sleep_s = float(os.environ.get("BALANCE_CHUNK_SLEEP", "0.05"))

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={local_dev}"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
if hasattr(jax.config, "jax_num_cpu_devices"):
    jax.config.update("jax_num_cpu_devices", local_dev)

import numpy as np

from oap_mllib_tpu.parallel import bootstrap

ran = bootstrap.initialize_distributed(coord, nproc, rank)
assert ran, "initialize_distributed returned False"

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.models.kmeans import KMeans
from oap_mllib_tpu.parallel import balance

ROWS, D, CHUNK = 6000, 16, 250
rng = np.random.default_rng(1234)  # SAME table on every rank
x = rng.normal(size=(ROWS, D)).astype(np.float32)


class SlowRows:
    """Row-sliceable wrapper that sleeps per slice on THIS rank — the
    deliberately slowed host.  The balance view slices one chunk at a
    time, so each chunk pays one sleep."""

    def __init__(self, base, per_slice_s):
        self._base = base
        self._sleep = per_slice_s
        self.shape = base.shape
        self.ndim = base.ndim
        self.dtype = base.dtype

    def __getitem__(self, idx):
        if self._sleep > 0:
            time.sleep(self._sleep)
        return self._base[idx]


data = SlowRows(x, sleep_s if rank == 1 else 0.0)

if mode == "weighted":
    set_config(
        capability_sharding="auto",
        rank_capability="0:1.0,1:0.25",
    )
elif mode == "equal":
    set_config(capability_sharding="off")
elif mode == "rebalance":
    # equal pinned capabilities: the static plan is equal, so only the
    # LIVE controller (riding the fleet rollups) can fix the skew
    set_config(
        capability_sharding="auto",
        rank_capability="1.0",
        rebalance_threshold=1.3,
        rebalance_patience=2,
    )
else:
    print(f"WORKER_ERROR rank={rank} unknown mode {mode}", flush=True)
    os._exit(4)

try:
    src = balance.local_sources(data, chunk_rows=CHUNK)
    t0 = time.monotonic()
    m = KMeans(
        k=4, seed=7, init_mode="random", max_iter=8, tol=0.0
    ).fit(src)
    wall = time.monotonic() - t0
except Exception as e:  # noqa: BLE001 — surface env markers
    import traceback

    traceback.print_exc()
    print(f"WORKER_ERROR rank={rank} {type(e).__name__}: {e}", flush=True)
    os._exit(4)

centers = np.asarray(m.cluster_centers_, np.float64)
digest = np.sort(centers.sum(axis=1)).round(6).tolist()
bal = getattr(m.summary, "balance", None)
flt = getattr(m.summary, "fleet", None)
print(
    "BALANCE rank=%d %s" % (rank, json.dumps(bal, sort_keys=True)),
    flush=True,
)
print(
    "FLEETROWS rank=%d %s" % (
        rank,
        json.dumps(
            {
                "per_rank_rows": (flt or {}).get("per_rank_rows"),
                "per_rank_capability": (flt or {}).get(
                    "per_rank_capability"),
            },
            sort_keys=True,
        ),
    ),
    flush=True,
)
print(
    "RESULT rank=%d %s" % (
        rank,
        json.dumps(
            {
                "ok": 1,
                "wall_s": round(wall, 4),
                "cost": float(m.summary.training_cost),
                "digest": digest,
                "centers": centers.round(10).tolist(),
            },
            sort_keys=True,
        ),
    ),
    flush=True,
)
