"""Flight recorder units (ISSUE 11): ring semantics, seq monotonicity,
the off-path contract, the append budget, and the crash-record tail."""

import json
import os
import time

import numpy as np
import pytest

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.telemetry import flightrec


@pytest.fixture(autouse=True)
def _clean():
    set_config(flight_recorder=0, crash_dir="")
    flightrec._reset_for_tests()
    yield
    set_config(flight_recorder=0, crash_dir="")
    flightrec._reset_for_tests()


class TestRing:
    def test_off_by_default_records_nothing(self):
        assert flightrec.record("span_open", "x") is None
        assert flightrec.tail() == []
        assert flightrec.last_seq() == -1
        assert flightrec.enabled() is False

    def test_negative_slot_count_raises(self):
        set_config(flight_recorder=-1)
        with pytest.raises(ValueError, match="flight_recorder"):
            flightrec.record("span_open", "x")

    def test_records_in_seq_order_with_payload(self):
        set_config(flight_recorder=16)
        s0 = flightrec.record("span_open", "lloyd_loop")
        s1 = flightrec.record("collective", "psum", "data|(4,8)")
        assert (s0, s1) == (0, 1)
        tail = flightrec.tail()
        assert [e["seq"] for e in tail] == [0, 1]
        assert tail[1]["kind"] == "collective"
        assert tail[1]["name"] == "psum"
        assert tail[1]["detail"] == "data|(4,8)"
        assert tail[0]["t"] <= tail[1]["t"]

    def test_wraparound_keeps_newest_and_constant_memory(self):
        set_config(flight_recorder=8)
        for i in range(30):
            flightrec.record("chunk", "prefetch", f"#{i}")
        tail = flightrec.tail()
        assert len(tail) == 8  # ring never grows past its slots
        assert [e["seq"] for e in tail] == list(range(22, 30))
        # seq keeps counting across wrap-around — monotonic forever
        assert flightrec.last_seq() == 29

    def test_tail_n_returns_newest_n(self):
        set_config(flight_recorder=32)
        for i in range(10):
            flightrec.record("chunk", "prefetch", f"#{i}")
        assert [e["seq"] for e in flightrec.tail(3)] == [7, 8, 9]

    def test_seq_monotonic_under_threads(self):
        import threading

        set_config(flight_recorder=64)
        seqs = []
        lock = threading.Lock()

        def worker():
            for _ in range(50):
                s = flightrec.record("chunk", "t")
                with lock:
                    seqs.append(s)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(seqs) == list(range(200))  # no duplicate seqs

    def test_drain_new_is_a_cursor(self):
        set_config(flight_recorder=16)
        flightrec.record("span_open", "a")
        flightrec.record("span_close", "a")
        first = flightrec.drain_new()
        assert [e["seq"] for e in first] == [0, 1]
        assert flightrec.drain_new() == []  # nothing new
        flightrec.record("span_open", "b")
        assert [e["seq"] for e in flightrec.drain_new()] == [2]

    def test_resize_rebuilds_ring(self):
        set_config(flight_recorder=4)
        flightrec.record("chunk", "x")
        set_config(flight_recorder=8)
        flightrec.record("chunk", "y")
        assert flightrec._recorder().slots == 8


class TestOverheadBudget:
    def test_append_budget_on_microbench(self):
        """Armed appends must stay under a measured per-event budget:
        the recorder rides hot seams (per chunk, per collective), so an
        append is a lock + tuple store — budget 50 us/event median,
        orders of magnitude above the real cost but tight enough to
        catch an accidental O(slots) append."""
        set_config(flight_recorder=256)
        n = 5000
        t0 = time.perf_counter()
        for i in range(n):
            flightrec.record("chunk", "bench", "#")
        per_event = (time.perf_counter() - t0) / n
        assert per_event < 50e-6, f"append cost {per_event*1e6:.1f} us"

    def test_recorder_off_is_one_config_check(self):
        """The off path allocates nothing and touches no ring — the
        20-fit microbench contract is priced by dev/fleet_gate.py; here
        we pin the mechanism: no recorder object exists when off."""
        assert flightrec.record("chunk", "x") is None
        assert flightrec._rec is None

    def test_twenty_fit_microbench_records_events_when_armed(self):
        """A 20-fit armed run actually lands events (the budget above
        is meaningless if nothing records) — streamed fits produce
        span + chunk events."""
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(flight_recorder=512)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(400, 4)).astype(np.float32)

        def gen():
            for lo in range(0, 400, 100):
                yield x[lo:lo + 100]

        for _ in range(3):
            src = ChunkSource(gen, 4, 100, n_rows=400)
            KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(src)
        kinds = {e["kind"] for e in flightrec.tail()}
        assert "chunk" in kinds and "span_open" in kinds, kinds


class TestCrashRecordTail:
    def test_crash_record_v2_embeds_tail(self, tmp_path):
        from oap_mllib_tpu.utils import recovery

        set_config(flight_recorder=128, crash_dir=str(tmp_path))
        for i in range(40):
            flightrec.record("chunk", "prefetch", f"#{i}")
        path = recovery.write_crash_record(
            "test.site", "transient", "boom"
        )
        rec = json.load(open(path))
        assert rec["version"] == 2
        tail = rec["flight_recorder"]
        assert len(tail) >= 32
        # the crash itself is the final event of the embedded tail
        assert tail[-1]["kind"] == "crash"
        assert tail[-1]["name"] == "test.site"
        seqs = [e["seq"] for e in tail]
        assert seqs == sorted(seqs)

    def test_crash_record_with_recorder_off_has_empty_tail(self, tmp_path):
        from oap_mllib_tpu.utils import recovery

        set_config(crash_dir=str(tmp_path))
        path = recovery.write_crash_record("s", "oom", "x")
        rec = json.load(open(path))
        assert rec["version"] == 2
        assert rec["flight_recorder"] == []
        os.unlink(path)
