"""Sparse (SciPy CSR) ingestion tests — ISSUE 12 satellite.

Spark accepts sparse vectors; this stack densifies — but per CHUNK /
per BLOCK at staging time (data/sparse.py), never the whole dataset up
front.  Covers: dense-parity through DenseTable and ChunkSource, fit
parity on K-Means and PCA, and the peak-host-bytes regression (the
per-chunk densify must never materialize the full dense table).
"""

import numpy as np
import pytest

scipy_sparse = pytest.importorskip("scipy.sparse")

from oap_mllib_tpu.config import set_config  # noqa: E402
from oap_mllib_tpu.data import sparse as sparse_mod  # noqa: E402
from oap_mllib_tpu.data.stream import ChunkSource  # noqa: E402
from oap_mllib_tpu.data.table import DenseTable  # noqa: E402
from oap_mllib_tpu.parallel.mesh import get_mesh  # noqa: E402


def _csr(rng, n=500, d=20, density=0.08, dtype=np.float32):
    return scipy_sparse.random(
        n, d, density=density, format="csr", dtype=dtype,
        random_state=np.random.RandomState(7),
    )


class TestDetection:
    def test_is_sparse(self, rng):
        x = _csr(rng)
        assert sparse_mod.is_sparse(x)
        assert sparse_mod.is_sparse(x.tocoo())
        assert not sparse_mod.is_sparse(np.zeros((3, 3)))
        assert not sparse_mod.is_sparse([[1, 2]])

    def test_nbytes_prices_the_csr_not_the_dense(self, rng):
        x = _csr(rng, n=2000, d=200, density=0.01)
        assert sparse_mod.nbytes(x) < 2000 * 200 * 4 / 5


class TestChunkSourceCSR:
    def test_round_trip_matches_dense(self, rng):
        x = _csr(rng)
        src = ChunkSource.from_array(x, chunk_rows=128)
        assert src.backing == "memory"
        assert src.n_rows == x.shape[0]
        np.testing.assert_allclose(src.to_array(), x.toarray())

    def test_densify_is_per_chunk(self, rng, monkeypatch):
        """The staging-time contract: no toarray call ever covers more
        rows than one chunk."""
        x = _csr(rng, n=1000, d=16)
        seen = []
        orig = scipy_sparse.csr_matrix.toarray

        def spy(self, *a, **k):
            seen.append(self.shape[0])
            return orig(self, *a, **k)

        monkeypatch.setattr(scipy_sparse.csr_matrix, "toarray", spy)
        src = ChunkSource.from_array(x, chunk_rows=128)
        src.to_array()
        assert seen and max(seen) <= 128

    def test_peak_host_bytes_stay_chunk_bounded(self, rng):
        """tracemalloc regression: iterating a CSR source allocates
        O(chunk) dense, far under the full dense table."""
        import tracemalloc

        n, d = 20_000, 50
        x = _csr(rng, n=n, d=d, density=0.02)
        src = ChunkSource.from_array(x, chunk_rows=512)
        dense_bytes = n * d * 4
        tracemalloc.start()
        for _chunk, _v in src:
            pass
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        # chunk buffer + staged copy + slack — an order of magnitude
        # under the 4 MB dense table
        assert peak < dense_bytes / 4, (peak, dense_bytes)


class TestDenseTableCSR:
    def test_table_matches_dense_build(self, rng):
        x = _csr(rng)
        mesh = get_mesh()
        ts = DenseTable.from_numpy(x, mesh)
        td = DenseTable.from_numpy(x.toarray(), mesh)
        assert ts.n_rows == td.n_rows
        np.testing.assert_array_equal(
            np.asarray(ts.data), np.asarray(td.data)
        )
        np.testing.assert_array_equal(
            np.asarray(ts.mask), np.asarray(td.mask)
        )

    def test_densify_into_is_blockwise(self, rng, monkeypatch):
        x = _csr(rng, n=1000, d=8)
        seen = []
        orig = scipy_sparse.csr_matrix.toarray

        def spy(self, *a, **k):
            seen.append(self.shape[0])
            return orig(self, *a, **k)

        monkeypatch.setattr(scipy_sparse.csr_matrix, "toarray", spy)
        out = np.zeros((1024, 8), np.float32)
        sparse_mod.densify_into(out, x, 1000, block_rows=256)
        assert seen and max(seen) <= 256
        np.testing.assert_allclose(out[:1000], x.toarray())


class TestFitParity:
    def test_kmeans_sparse_matches_dense(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _csr(rng, n=400, d=12, density=0.2)
        md = KMeans(k=3, seed=2, max_iter=4).fit(x.toarray())
        ms = KMeans(k=3, seed=2, max_iter=4).fit(x)
        np.testing.assert_allclose(
            ms.cluster_centers_, md.cluster_centers_, atol=1e-6
        )
        np.testing.assert_allclose(
            ms.summary.training_cost, md.summary.training_cost, rtol=1e-6
        )

    def test_pca_sparse_matches_dense(self, rng):
        from oap_mllib_tpu.models.pca import PCA

        x = _csr(rng, n=400, d=12, density=0.2)
        md = PCA(k=3).fit(x.toarray())
        ms = PCA(k=3).fit(x)
        np.testing.assert_allclose(
            np.abs(ms.components_), np.abs(md.components_), atol=1e-6
        )
        np.testing.assert_allclose(
            ms.explained_variance_, md.explained_variance_, atol=1e-6
        )

    def test_sparse_streamed_route_matches(self, rng):
        """A CSR through the STREAMED route (budget-pinned) densifies
        per chunk and matches the dense streamed fit exactly."""
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _csr(rng, n=400, d=12, density=0.2)
        set_config(scale_policy="pin:streamed")
        try:
            ms = KMeans(k=3, seed=2, max_iter=4).fit(x)
            md = KMeans(k=3, seed=2, max_iter=4).fit(x.toarray())
            np.testing.assert_allclose(
                ms.cluster_centers_, md.cluster_centers_, atol=1e-6
            )
            assert ms.summary.route["route"] == "streamed"
        finally:
            set_config(scale_policy="auto")

    def test_sparse_fallback_path(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _csr(rng, n=200, d=8, density=0.3)
        set_config(device="cpu")
        try:
            m = KMeans(k=3, seed=2, max_iter=4).fit(x)
            assert not m.summary.accelerated
            assert np.all(np.isfinite(m.cluster_centers_))
        finally:
            set_config(device="auto")
