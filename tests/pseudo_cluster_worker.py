"""Worker process for the 2-process pseudo-cluster test.

Each worker is one rank of a real ``jax.distributed`` world over
127.0.0.1 — the analog of one Spark executor in the reference's only
multi-rank test, the 2-executor pseudo-YARN cluster
(reference dev/ci-test.sh:60-62, dev/test-cluster/setup-cluster.sh).

Invoked as:  python pseudo_cluster_worker.py RANK NPROC COORD LOCAL_DEVICES

Prints one JSON line of results for the parent test to compare against
the single-process oracle.
"""

import json
import sys

rank, nproc = int(sys.argv[1]), int(sys.argv[2])
coord, local_dev = sys.argv[3], int(sys.argv[4])

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    # older jax lines have no jax_num_cpu_devices config option; the env
    # flag must be in place before the backend initializes
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={local_dev}"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
if hasattr(jax.config, "jax_num_cpu_devices"):
    jax.config.update("jax_num_cpu_devices", local_dev)

import numpy as np

from oap_mllib_tpu.parallel import bootstrap

ran = bootstrap.initialize_distributed(coord, nproc, rank)
assert ran, "initialize_distributed returned False for a multi-process world"
assert jax.process_count() == nproc, jax.process_count()
assert len(jax.devices()) == nproc * local_dev, len(jax.devices())

from oap_mllib_tpu.models.kmeans import KMeans
from oap_mllib_tpu.models.pca import PCA

# deterministic global dataset; each rank holds only its half (the
# "no host ever holding the full table" contract, data/table.py)
rng = np.random.default_rng(123)
proto = rng.normal(size=(5, 12)).astype(np.float32) * 3.0
x = (proto[rng.integers(5, size=4000)]
     + rng.normal(size=(4000, 12)).astype(np.float32) * 0.25)
half = x[rank * 2000 : (rank + 1) * 2000]

# default init = k-means||: the device-side rounds must run multi-host
# (round 1 crashed here — host indexing on a non-addressable array)
m = KMeans(k=5, seed=7, max_iter=30).fit(half)
assert m.summary.accelerated

# weighted fit exercises the collective sample_weight path
w_local = np.ones((2000,), np.float32)
w_local[:100] = 2.5
mw = KMeans(k=5, seed=7, init_mode="random", max_iter=10).fit(
    half, sample_weight=w_local
)

# UNEVEN shards: rank 0 holds 1999 valid rows (padded to 2000 mid-array),
# rank 1 holds 2000 — random init must never sample the padding row and
# must reach every valid row (valid->padded index mapping)
uneven = x[:1999] if rank == 0 else x[1999:3999]
mu = KMeans(k=5, seed=11, init_mode="random", max_iter=15).fit(uneven)

p = PCA(k=4).fit(half)

# model-axis fits: model_parallel=2 arranges the 4 global devices as a
# (data=2, model=2) mesh whose DATA axis crosses the process boundary —
# the feature-sharded K-Means Lloyd (kmeans_ops.lloyd_run_model_sharded)
# and the model-sharded PCA Gram run their psums/all_gathers across a
# real 2-process world, not just the single-host virtual mesh
from oap_mllib_tpu.config import set_config

set_config(model_parallel=2)
m_mp = KMeans(k=5, seed=7, init_mode="random", max_iter=15).fit(half)
assert m_mp.summary.accelerated
p_mp = PCA(k=4).fit(half)
assert p_mp.summary["mesh_shape"] == {"data": 2, "model": 2}
set_config(model_parallel=1)

# --- streamed (out-of-core) fits: each rank streams its OWN shard as a
# local ChunkSource; sums/Gram/init state reduce across processes
# (ops/stream_ops._psum_host / _allgather_host — the DCN analog of the
# mesh path's psums).  Every rank must produce IDENTICAL results.
from oap_mllib_tpu.data.stream import ChunkSource

ms = KMeans(k=5, seed=7, max_iter=30).fit(
    ChunkSource.from_array(half, chunk_rows=512)
)
assert getattr(ms.summary, "streamed", False)
ms_rand = KMeans(k=5, seed=11, init_mode="random", max_iter=15).fit(
    ChunkSource.from_array(half, chunk_rows=512)
)
ps = PCA(k=4).fit(ChunkSource.from_array(half, chunk_rows=512))
assert ps.summary["streamed"] and ps.summary["n_rows"] == 4000

# --- ALS: each rank contributes its LOCAL ratings shard (the per-rank
# partitions of the reference's shuffle, ALSDALImpl.scala:95-109).  This
# exercises the multi-process branches of exchange_ratings (allgathered
# bucket counts + make_array_from_process_local_data), the allgathered
# id-maxima resolution in ALS.fit, and the rank-local sharded factor path
# (no host materializes (n_users, rank); gather is on-demand collective).
from oap_mllib_tpu.models.als import ALS

rng_als = np.random.default_rng(77)
NU, NI, RANK = 60, 40, 3
xt = rng_als.normal(size=(NU, RANK)).astype(np.float32)
yt = rng_als.normal(size=(NI, RANK)).astype(np.float32)
au = rng_als.integers(NU, size=1200).astype(np.int64)
ai = rng_als.integers(NI, size=1200).astype(np.int64)
au[0], ai[0] = NU - 1, NI - 1  # pin the id maxima deterministically
ar = ((xt[au] * yt[ai]).sum(1)
      + rng_als.normal(size=1200).astype(np.float32) * 0.1).astype(np.float32)
# UNEVEN split: 590 vs 610 edges
cut = 590
sl = slice(0, cut) if rank == 0 else slice(cut, None)

als_out = {}
for implicit, tag in ((True, "imp"), (False, "exp")):
    m_als = ALS(rank=RANK, max_iter=3, reg_param=0.1, alpha=0.8,
                implicit_prefs=implicit, seed=3).fit(au[sl], ai[sl], ar[sl])
    assert m_als.summary["accelerated"]
    assert m_als.summary.get("sharded_factors"), "factors not kept sharded"
    als_out[f"als_{tag}_uf"] = np.asarray(m_als.user_factors_).tolist()
    als_out[f"als_{tag}_if"] = np.asarray(m_als.item_factors_).tolist()

# item-sharded 2-D layout across the real 2-process world: a second
# shuffle by item block, Y block-sharded over the global mesh, all_gather
# exchanges inside the scan, and the on-demand item-factor gather becomes
# a COLLECTIVE (every rank touches item_factors_ together)
set_config(als_item_layout="sharded")
m_sh = ALS(rank=RANK, max_iter=3, reg_param=0.1, alpha=0.8,
           implicit_prefs=True, seed=3).fit(au[sl], ai[sl], ar[sl])
assert m_sh.summary["item_layout"] == "sharded"
als_out["als_sh_uf"] = np.asarray(m_sh.user_factors_).tolist()
als_out["als_sh_if"] = np.asarray(m_sh.item_factors_).tolist()
set_config(als_item_layout="auto")

# --- streamed ALS composed with the REAL 2-process mesh: each rank
# streams its LOCAL triples through a ChunkSource; the prep
# redistributes edges by block over the process boundary (chunked
# fixed-shape allgather) and the fit walks host-resident grouped
# layouts through each device (ops/als_block_stream).  Forced grouped:
# the tiny test data would otherwise trip the COO blowup guard.
set_config(als_kernel="grouped")
trip = np.stack(
    [au[sl].astype(np.float64), ai[sl].astype(np.float64),
     ar[sl].astype(np.float64)], axis=1,
)
m_st = ALS(rank=RANK, max_iter=3, reg_param=0.1, alpha=0.8,
           implicit_prefs=True, seed=3).fit(
    ChunkSource.from_array(trip, chunk_rows=256)
)
assert m_st.summary.get("streamed"), m_st.summary
assert m_st.summary.get("block_parallel"), m_st.summary
als_out["als_st_uf"] = np.asarray(m_st.user_factors_).tolist()
als_out["als_st_if"] = np.asarray(m_st.item_factors_).tolist()

# the 2-D item-sharded streamed composition across the process boundary:
# the single-sweep double redistribution (user AND item keyed), the
# per-half-iteration replicate() of the other side's block factors, and
# the collective item-factor gather all cross processes here
set_config(als_item_layout="sharded")
m_st2 = ALS(rank=RANK, max_iter=3, reg_param=0.1, alpha=0.8,
            implicit_prefs=True, seed=3).fit(
    ChunkSource.from_array(trip, chunk_rows=256)
)
assert m_st2.summary.get("streamed"), m_st2.summary
assert m_st2.summary["item_layout"] == "sharded", m_st2.summary
als_out["als_st_sh_uf"] = np.asarray(m_st2.user_factors_).tolist()
als_out["als_st_sh_if"] = np.asarray(m_st2.item_factors_).tolist()
set_config(als_item_layout="auto", als_kernel="auto")

# --- PySpark-adapter distributed ingestion: a mocked partitioned
# DataFrame (the duck-typed rdd.mapPartitionsWithIndex surface) feeds
# each process ONLY its partitions (pid % world == rank), which the
# adapter passes as this process's local shard of the multi-host fit
# (compat/pyspark._collect_local_partitions — the executor-local
# conversion of the reference, OneDAL.scala:92-166).  No process ever
# collects the whole dataset.
from oap_mllib_tpu.compat import pyspark as compat_pyspark


class _PartDF:
    """Minimal partitioned-DataFrame mock: rows split into n_parts
    contiguous partitions; mapPartitionsWithIndex hands each (pid,
    iterator) to the filter like Spark would."""

    def __init__(self, cols, n_parts):
        self._cols, self._nparts = cols, n_parts

    @property
    def columns(self):
        return list(self._cols)

    def select(self, *names):
        return _PartDF({n: self._cols[n] for n in names}, self._nparts)

    def collect(self):
        names = list(self._cols)
        n = len(self._cols[names[0]])
        return [tuple(self._cols[c][j] for c in names) for j in range(n)]

    def count(self):
        # the adapter cross-checks allgathered kept-row counts against
        # this (compat/pyspark._collect_local_partitions)
        return len(self._cols[next(iter(self._cols))])

    @property
    def rdd(self):
        rows = self.collect()
        parts = np.array_split(np.arange(len(rows)), self._nparts)

        class _Res:
            def __init__(self, out):
                self._out = out

            def collect(self):
                return self._out

        class _RDD:
            def mapPartitionsWithIndex(self, f):
                out = []
                for pid, idx in enumerate(parts):
                    out.extend(f(pid, iter([rows[j] for j in idx])))
                return _Res(out)

        return _RDD()


pdf = _PartDF({"features": [list(row) for row in x]}, 8)
am = compat_pyspark.KMeans(k=5, seed=7, maxIter=30).fit(pdf)
assert am.summary.accelerated

rdf = _PartDF(
    {
        "user": [int(v) for v in au],
        "item": [int(v) for v in ai],
        "rating": [float(v) for v in ar],
    },
    6,
)
a_als = compat_pyspark.ALS(rank=RANK, maxIter=3, regParam=0.1, alpha=0.8,
                           implicitPrefs=True, seed=3, userCol="user",
                           itemCol="item", ratingCol="rating",
                           coldStartStrategy="drop").fit(rdf)
# the cold-start seen sets must be WORLD-consistent even though each
# rank ingested different partitions (compat/spark._global_unique)
seen_u = sorted(int(v) for v in a_als._inner._seenUsers)

print(
    "RESULT "
    + json.dumps(
        {
            "rank": rank,
            "kmeans_cost": float(m.summary.training_cost),
            "kmeans_iters": int(m.summary.num_iter),
            "weighted_cost": float(mw.summary.training_cost),
            "uneven_cost": float(mu.summary.training_cost),
            "pca_var": np.asarray(p.explained_variance_).tolist(),
            "pca_pc0_abs": np.abs(np.asarray(p.components_)[:, 0]).tolist(),
            "kmeans_mp_cost": float(m_mp.summary.training_cost),
            "kmeans_mp_iters": int(m_mp.summary.num_iter),
            "pca_mp_var": np.asarray(p_mp.explained_variance_).tolist(),
            "streamed_cost": float(ms.summary.training_cost),
            "streamed_iters": int(ms.summary.num_iter),
            "streamed_rand_cost": float(ms_rand.summary.training_cost),
            "streamed_pca_var": np.asarray(ps.explained_variance_).tolist(),
            "streamed_pca_pc0_abs": np.abs(
                np.asarray(ps.components_)[:, 0]
            ).tolist(),
            "adapter_mp_cost": float(am.summary.training_cost),
            "adapter_als_uf": np.asarray(a_als.userFactors).tolist(),
            "adapter_seen_users": seen_u,
            **als_out,
        }
    ),
    flush=True,
)
