"""Traffic-plane pseudo-cluster worker (ISSUE 16).

One replica of a REAL ``jax.distributed`` serving fleet driving the
async traffic plane end to end:

1. **Sharded-sweep parity** — shard deterministic ALS factor tables
   onto the live multi-process mesh (``sweep.shard_factors`` — the
   elastic redistribution pass), run the ring-rotated factor-sharded
   full sweep, and assert IN-PROCESS that ids AND score bits match the
   single-process reference (``ALSModel._top_k_scores``).  Prints
   ``PARITY_OK`` + a digest the parent cross-checks across ranks.
2. **Jittered storm** — waves of jittered-size requests through a
   :class:`serving.TrafficQueue` (submit -> future -> result walls),
   fleet heartbeats between waves over the deadline-watchdogged host
   collective plane, and a zero-steady-state-compile assertion from the
   XLA ground truth.  Prints ``STORM_OK rank= reqs= p50_ms= p99_ms=
   compiles=``.
3. **Loud shedding** (rank 0) — synthetic tight knobs drive one shed of
   each reason (queue_full / budget / deadline) with zero OOM.  Prints
   ``SHED_OK sheds=3``.

Modes (env ``TRAFFIC_WORKER_MODE``):

- ``healthy`` — every rank runs all legs and exits 0.
- ``evict`` — rank 1 SIGKILLs itself at the start of storm wave 1 (a
  preempted replica); rank 0's next heartbeat converts into a
  ``CollectiveTimeoutError`` which the :class:`ReplicaGuard` absorbs:
  the survivor prints ``EVICTED``, keeps answering the remaining waves
  in local-only mode, and still holds the p99 and zero-compile
  contracts.
- ``bench`` — the ``serving_kmeans_qps_mp`` headline: a sustained
  storm through the async queue, printing ``BENCH_QPS rank=0 qps=
  p50_ms= p99_ms=`` for bench.py to parse.
- ``trace`` — the ISSUE 19 observability world: request tracing
  (``serve_trace_sample=1.0``) + the SLO engine + the flight recorder
  + the JSONL telemetry sink armed BEFORE the leg-1 sharded sweep, so
  its ring-hop rotations and a traced storm's request ledgers land in
  per-rank sinks (``$TRAFFIC_TRACE_SINK.rank<r>``) that the parent
  merges through ``dev/oaptrace.py``.  Every answered future must
  carry a finalized ledger whose stages sum to its wall within 5%.
  Prints ``TRACE_OK rank= reqs= missing= bad_cov= sampled=``.
- ``drill`` — the ISSUE 18 request-lifecycle chaos drill: a >=200
  request storm with armed ``serve.dispatch`` transient faults (the
  retry envelope), an injected ``serve.batch`` poison plus real
  NaN-payload requests at known indices (bisection + quarantine),
  and rank 1 SIGKILLed mid-storm (eviction).  The survivor must
  resolve EVERY accepted future — answered bit-identically to direct
  ``handle.predict`` or failed with a classified ``ServeError`` —
  with zero steady-state compiles, print ``DRILL_OK`` with the exact
  counters, then re-form the leg-1 sharded sweep on its local layout
  (``shard_factors_local``) and prove bit-identical answers
  (``REFORM_OK``).

Invoked as:  python pseudo_cluster_worker_traffic.py RANK NPROC COORD LOCAL_DEV
(the standard worker argv — the shared _launch_world plumbing spawns it).
"""

import hashlib
import os
import sys
import time

rank, nproc = int(sys.argv[1]), int(sys.argv[2])
coord, local_dev = sys.argv[3], int(sys.argv[4])
mode = os.environ["TRAFFIC_WORKER_MODE"]
crash_dir = os.environ["TRAFFIC_CRASH_DIR"]

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={local_dev}"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
if hasattr(jax.config, "jax_num_cpu_devices"):
    jax.config.update("jax_num_cpu_devices", local_dev)

import numpy as np

if nproc > 1:
    from oap_mllib_tpu.parallel import bootstrap

    ran = bootstrap.initialize_distributed(coord, nproc, rank)
    assert ran, "initialize_distributed returned False"

from oap_mllib_tpu import serving
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.models.kmeans import KMeans
from oap_mllib_tpu.utils import progcache

# the heartbeat deadline is the eviction mechanism under test: well
# under the parent's watchdog, well over a healthy heartbeat
set_config(collective_timeout=10.0, crash_dir=crash_dir)


def _exit_barrier(tag, wait=True):
    # collective-free exit barrier: the first replica to _exit would
    # tear down the coordination service under its still-working
    # peers — wait until every rank has filed its done marker.  Rank 0
    # HOSTS the coordination service, so it must exit last: a peer
    # still in its poll sleep when the leader dies gets a fatal
    # "leader task died" abort from the error-polling thread.
    open(os.path.join(crash_dir, f"{tag}.done.rank{rank}"), "w").close()
    if wait:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not all(
            os.path.exists(os.path.join(crash_dir, f"{tag}.done.rank{r}"))
            for r in range(nproc)
        ):
            time.sleep(0.05)
        if rank == 0 and nproc > 1:
            time.sleep(1.0)
    os._exit(0)

if mode == "trace":
    # arm the whole observability plane BEFORE the leg-1 sharded sweep
    # so its ring-hop rotations land in the flight recorder, and tag
    # this process's rank so trace ids and sink files are per-rank
    set_config(
        process_id=rank,
        num_processes=nproc,
        flight_recorder=4096,
        telemetry_log=os.environ["TRAFFIC_TRACE_SINK"],
        serve_trace_sample=1.0,
        serve_slo_p99_ms=float(os.environ.get("TRAFFIC_SLO_P99_MS", "500")),
    )

# hosts whose jax build forms worlds but cannot RUN multiprocess
# computations (the pseudo-cluster CPU backend) die inside the sharded
# sweep with one of these — trace mode degrades to a collective-free
# traced storm there instead of losing the whole leg
_SHARDED_UNSUPPORTED = (
    "Multiprocess computations aren't implemented",
    "UNIMPLEMENTED",
)

# -- leg 1: multi-process sharded sweep, bit-identical to the reference
sweep_ok = True
if mode != "bench":
    from oap_mllib_tpu.models.als import ALSModel
    from oap_mllib_tpu.parallel.mesh import get_mesh
    from oap_mllib_tpu.serving import sweep

    try:
        prng = np.random.default_rng(123)
        uf = prng.normal(size=(96, 5)).astype(np.float32)
        itf = prng.normal(size=(64, 5)).astype(np.float32)
        mesh = get_mesh()
        ub, uoff, upp = sweep.shard_factors(uf, mesh)
        ib, ioff, ipp = sweep.shard_factors(itf, mesh)
        sharded = ALSModel(
            None, None,
            sharded_user=(ub, uoff, upp), sharded_item=(ib, ioff, ipp),
        )
        ids, scores = sweep.recommend_for_all_users(
            sharded, 8, with_scores=True)
        ref = ALSModel(uf, itf)
        ids_ref, s_ref = ref._top_k_scores(uf, itf, 8)
        assert np.array_equal(ids, ids_ref), "sharded sweep ids diverge"
        assert np.array_equal(scores, s_ref), \
            "sharded sweep score bits diverge"
        digest = hashlib.sha256(
            ids.tobytes() + scores.tobytes()).hexdigest()[:16]
        print(f"PARITY_OK rank={rank} digest={digest}", flush=True)
    except Exception as e:
        if mode == "trace" and any(
            m in repr(e) for m in _SHARDED_UNSUPPORTED
        ):
            sweep_ok = False
            print(f"SWEEP_SKIP rank={rank}", flush=True)
        else:
            raise

# -- serve one replicated model per replica (the fleet contract)
rng = np.random.default_rng(77)
if mode == "bench" or (mode == "trace" and not sweep_ok):
    # the QPS headline prices SERVING, not fitting: identical synthetic
    # centers on every replica (no collective — the leg runs even on
    # hosts whose jax build cannot fit across processes).  A
    # sweep-skipped trace world takes the same path: the tracing plane
    # prices requests, not the fit that made the model.
    from oap_mllib_tpu.models.kmeans import KMeansModel

    model = KMeansModel(rng.normal(size=(4, 8)).astype(np.float32))
else:
    x = rng.normal(size=(600, 8)).astype(np.float32)
    model = KMeans(k=4, seed=5, init_mode="random", max_iter=4).fit(x)
handle = serving.serve(model)
handle.warmup(128)

if mode == "bench":
    n_req = int(os.environ.get("TRAFFIC_BENCH_REQUESTS", "200"))
    reqs = [
        rng.normal(size=(int(s), 8)).astype(np.float32)
        for s in rng.integers(5, 128, size=n_req)
    ]
    with serving.TrafficQueue(handle) as q:
        for b in reqs[:16]:  # warm wave: async path + buckets hot
            q.submit(b, deadline_ms=60_000).result(timeout=60)
        t0 = time.perf_counter()
        subs = [
            (time.perf_counter(), q.submit(b, deadline_ms=120_000))
            for b in reqs
        ]
        walls = []
        for ts, f in subs:
            f.result(timeout=120)
            walls.append(time.perf_counter() - ts)
        total = time.perf_counter() - t0
    walls.sort()
    p50 = walls[len(walls) // 2]
    p99 = walls[min(len(walls) - 1, int(len(walls) * 0.99))]
    print(
        f"BENCH_QPS rank={rank} qps={n_req / total:.1f} "
        f"p50_ms={p50 * 1e3:.3f} p99_ms={p99 * 1e3:.3f}",
        flush=True,
    )
    _exit_barrier("bench")

# -- drill mode: durable futures under replica death + poison + retries
if mode == "drill":
    from oap_mllib_tpu.telemetry import metrics as _tm

    set_config(serve_queue_depth=1024, serve_retry_limit=3,
               serve_retry_backoff=0.005)
    guard = serving.ReplicaGuard()
    q = serving.TrafficQueue(handle)
    # warm wave: async path, bucket family, and the heartbeat shapes
    # all hot BEFORE the chaos arms — the zero-compile clock starts
    # here.  Coalesced flushes bucket on the SUM of request rows (the
    # 1024-row flush bound), so the family warms to that bound, not
    # just the largest single request.
    handle.warmup(1024)
    for b in [
        rng.normal(size=(int(s), 8)).astype(np.float32)
        for s in rng.integers(5, 128, size=12)
    ]:
        q.submit(b, deadline_ms=120_000).result(timeout=120)
    with guard.leg():
        if nproc > 1:
            serving.heartbeat(requests=handle.requests,
                              queue_depth=q.depth())
    compile_snap = progcache.xla_compile_count()
    # the storm: two transient dispatcher faults (retry envelope), one
    # injected coalesced-batch poison (bisection with innocents), and
    # three REAL NaN-payload requests at known indices (data poison the
    # finite-guard quarantines deterministically)
    set_config(fault_spec="serve.dispatch:fail=2,serve.batch:nan=1")
    n_req = 220
    per_wave = n_req // 5
    poison_at = {31, 97, 171}
    reqs = []
    for i, s in enumerate(rng.integers(5, 128, size=n_req)):
        b = rng.normal(size=(int(s), 8)).astype(np.float32)
        if i in poison_at:
            b[0, 0] = np.nan
        reqs.append(b)
    futs = {}
    announced = False
    for w in range(5):
        if rank == 1 and nproc > 1 and w == 1:
            import signal

            os.kill(os.getpid(), signal.SIGKILL)  # a preempted replica
        wave = range(w * per_wave, (w + 1) * per_wave)
        with guard.leg():
            for i in wave:
                futs[i] = q.submit(reqs[i], deadline_ms=120_000)
            for i in wave:
                try:
                    futs[i].result(timeout=120)
                except Exception:
                    pass  # classified failures audited below
            if not guard.local_only and nproc > 1:
                serving.heartbeat(requests=handle.requests,
                                  queue_depth=q.depth())
        if guard.local_only and not announced:
            announced = True
            err = type(guard.last_error).__name__
            print(f"EVICTED rank={rank} wave={w} err={err}", flush=True)
    steady_compiles = progcache.xla_compile_count() - compile_snap
    q.close()
    # the request-lifecycle audit: EVERY accepted future resolved —
    # exactly the poison requests quarantined, everything else answered
    # bit-identically to a direct predict on the same handle
    unresolved = sum(1 for f in futs.values() if not f.done())
    assert unresolved == 0, f"{unresolved} futures leaked"
    poison, answered = [], 0
    for i, f in sorted(futs.items()):
        exc = f.exception()
        if exc is None:
            answered += 1
            assert np.array_equal(f.result(), handle.predict(reqs[i])), (
                f"req {i}: async answer diverges from direct predict"
            )
        else:
            assert isinstance(exc, serving.ServeError), (
                f"req {i}: unclassified failure {exc!r}"
            )
            assert exc.reason == "poison", f"req {i}: {exc.reason}"
            poison.append(i)
    assert set(poison) == poison_at, (poison, poison_at)
    retried = int(_tm.family_total("oap_serve_retries_total"))
    bisects = int(_tm.family_total("oap_serve_bisect_total"))
    assert retried >= 1, "dispatcher transients never retried"
    assert bisects >= 1, "poison batches never bisected"
    print(
        f"DRILL_OK rank={rank} submitted={n_req} answered={answered} "
        f"poison={len(poison)} retried={retried} bisects={bisects} "
        f"unresolved={unresolved} compiles={steady_compiles}",
        flush=True,
    )
    # -- re-form the leg-1 sharded sweep on the survivor's live layout:
    # the old mesh spans the dead rank, so the sweep must refuse it
    # (classified, pre-launch) and the reform hook re-shards the host
    # tables across LOCAL devices — answers stay bit-identical
    if rank == 0 and nproc > 1:
        assert serving.fleet_evicted(), "drill requires an eviction"
        ids2, s2 = sweep.recommend_for_all_users(
            sharded, 8, with_scores=True,
            reform=lambda exc: ALSModel(
                None, None,
                sharded_user=sweep.shard_factors_local(uf),
                sharded_item=sweep.shard_factors_local(itf),
            ),
        )
        assert np.array_equal(ids2, ids_ref), "re-formed sweep ids diverge"
        assert np.array_equal(s2, s_ref), "re-formed sweep score bits diverge"
        reforms = int(_tm.family_total("oap_serve_sweep_reforms_total"))
        rdigest = hashlib.sha256(
            ids2.tobytes() + s2.tobytes()
        ).hexdigest()[:16]
        print(f"REFORM_OK rank={rank} reforms={reforms} digest={rdigest}",
              flush=True)
    open(os.path.join(crash_dir, f"traffic.done.rank{rank}"), "w").close()
    os._exit(0)

# -- trace mode: a traced storm on top of the leg-1 sharded sweep; the
# per-rank JSONL sinks are the parent gate's oaptrace input
if mode == "trace":
    from oap_mllib_tpu.serving import reqtrace
    from oap_mllib_tpu.telemetry import export

    handle.warmup(1024)
    guard = serving.ReplicaGuard()
    with guard.leg():
        if nproc > 1 and sweep_ok:
            # one heartbeat = one collective flightrec event per rank —
            # the clock-alignment anchor oaptrace merges the sinks on
            # (collectives proven live by leg 1; a sweep-skipped host
            # would die here the same way)
            serving.heartbeat(requests=handle.requests)
    n_req = int(os.environ.get("TRAFFIC_TRACE_REQUESTS", "40"))
    reqs = [
        rng.normal(size=(int(s), 8)).astype(np.float32)
        for s in rng.integers(5, 128, size=n_req)
    ]
    with serving.TrafficQueue(handle) as q:
        futs = [q.submit(b, deadline_ms=120_000) for b in reqs]
        for f in futs:
            f.result(timeout=120)
    ledgers = [reqtrace.ledger_of(f) for f in futs]
    missing = sum(1 for lg in ledgers if lg is None or not lg.outcome)
    bad_cov = sum(
        1 for lg in ledgers
        if lg is not None and lg.wall_s > 1e-6
        and abs(lg.stage_sum() - lg.wall_s) > 0.05 * lg.wall_s
    )
    sampled = sum(
        1 for lg in ledgers if lg is not None and lg.ctx.sampled
    )
    # os._exit skips atexit: drain the flight recorder + final metrics
    # snapshot into the sink NOW so the parent's merge sees the ring
    # hops and request records
    export.shutdown()
    print(
        f"TRACE_OK rank={rank} reqs={n_req} missing={missing} "
        f"bad_cov={bad_cov} sampled={sampled} sweep={int(sweep_ok)}",
        flush=True,
    )
    _exit_barrier("trace")

# -- leg 2: jittered storm, heartbeats between waves, zero steady compiles
waves = [
    [
        rng.normal(size=(int(s), 8)).astype(np.float32)
        for s in rng.integers(5, 128, size=12)
    ]
    for _ in range(3)
]
guard = serving.ReplicaGuard()
walls = []
announced = False
compile_snap = None
q = serving.TrafficQueue(handle)
for w, wave in enumerate(waves):
    if mode == "evict" and rank == 1 and nproc > 1 and w == 1:
        import signal

        os.kill(os.getpid(), signal.SIGKILL)  # a preempted replica
    with guard.leg():
        futs = [
            (time.perf_counter(), q.submit(b, deadline_ms=120_000))
            for b in wave
        ]
        for ts, f in futs:
            f.result(timeout=120)
            walls.append(time.perf_counter() - ts)
        if not guard.local_only and nproc > 1:
            view = serving.heartbeat(
                requests=handle.requests, queue_depth=q.depth()
            )
            if w == 0:
                print(f"FLEET rank={rank} world={view['world']}", flush=True)
    if guard.local_only and not announced:
        announced = True
        err = type(guard.last_error).__name__
        print(f"EVICTED rank={rank} wave={w} err={err}", flush=True)
    if w == 0:
        # wave 0 is the warm wave (first heartbeat shapes included);
        # everything after must compile NOTHING, and the latency
        # contract (p99 vs p50) is judged on steady-state waves only
        compile_snap = progcache.xla_compile_count()
        walls = []
q.close()
steady_compiles = progcache.xla_compile_count() - compile_snap
walls.sort()
p50 = walls[len(walls) // 2]
p99 = walls[min(len(walls) - 1, int(len(walls) * 0.99))]
print(
    f"STORM_OK rank={rank} reqs={len(walls)} p50_ms={p50 * 1e3:.3f} "
    f"p99_ms={p99 * 1e3:.3f} compiles={steady_compiles} "
    f"local_only={guard.local_only}",
    flush=True,
)

# -- leg 3 (rank 0): one loud shed of each reason, zero OOM
if rank == 0:
    sheds = []
    set_config(serve_queue_depth=1)
    q2 = serving.TrafficQueue(handle, start=False)
    held = q2.submit(waves[0][0])
    try:
        q2.submit(waves[0][1])
    except serving.ShedError as e:
        sheds.append(e.reason)
    set_config(serve_queue_depth=256, memory_budget_hbm="2K",
               serve_shed_headroom=0.5)
    try:
        q2.submit(np.zeros((512, 8), np.float32))
    except serving.ShedError as e:
        sheds.append(e.reason)
    set_config(memory_budget_hbm="")
    late = q2.submit(waves[0][2], deadline_ms=1.0)
    time.sleep(0.05)
    q2.pump()
    if isinstance(late.exception(), serving.ShedError):
        sheds.append(late.exception().reason)
    assert held.result(timeout=30) is not None  # admitted work still answers
    q2.close()
    assert sheds == ["queue_full", "budget", "deadline"], sheds
    print(f"SHED_OK rank={rank} sheds={len(sheds)}", flush=True)

print(
    f"TRAFFIC_OK rank={rank} reqs={len(walls)} local_only={guard.local_only}",
    flush=True,
)
# barrier wait is skipped once the fleet is evicted — the dead peer
# will never file its marker
_exit_barrier("traffic", wait=not guard.local_only)
