"""Config-surface coverage: every field is read somewhere, documented,
and env-overridable — the audit VERDICT r3 item 6 asked for (the round-3
`Config.seed` was documented but read by nothing)."""

import dataclasses
import os
import re

import numpy as np
import pytest

from oap_mllib_tpu.config import Config, set_config

HERE = os.path.dirname(os.path.abspath(__file__))
PKG = os.path.join(HERE, "..", "oap_mllib_tpu")
DOCS = os.path.join(HERE, "..", "docs", "configuration.md")


def _package_source_without_config():
    parts = []
    for root, _, files in os.walk(PKG):
        for f in files:
            if f.endswith(".py") and f != "config.py":
                with open(os.path.join(root, f)) as fh:
                    parts.append(fh.read())
    return "\n".join(parts)


class TestConfigCoverage:
    def test_every_field_is_read_somewhere(self):
        """A Config field nothing reads is dead weight that will drift
        from its docs (the round-3 seed bug).  Accepted read patterns:
        ``cfg.NAME`` / ``conf.NAME`` / ``config.NAME`` /
        ``get_config().NAME``."""
        src = _package_source_without_config()
        for f in dataclasses.fields(Config):
            pat = rf"(cfg|conf|config|get_config\(\))\.{f.name}\b"
            assert re.search(pat, src), (
                f"Config.{f.name} is read nowhere in the package — wire it "
                "or delete it (and its docs row)"
            )

    def test_every_field_is_documented(self):
        """docs/configuration.md's field table and the dataclass must
        list the same fields, both directions."""
        with open(DOCS) as fh:
            doc = fh.read()
        fields = {f.name for f in dataclasses.fields(Config)}
        documented = set(re.findall(r"^\| `(\w+)` \|", doc, re.M))
        assert fields - documented == set(), "undocumented Config fields"
        assert documented - fields == set(), "docs rows for deleted fields"

    def test_env_override_every_field(self, monkeypatch):
        """OAP_MLLIB_TPU_<FIELD> overrides each field with the right
        type coercion."""
        types = {"bool": bool, "int": int, "float": float, "str": str}
        samples = {bool: "true", int: "7", float: "2.5", str: "xyz"}
        for f in dataclasses.fields(Config):
            t = types.get(str(f.type), str)
            monkeypatch.setenv(
                "OAP_MLLIB_TPU_" + f.name.upper(), samples[t]
            )
        cfg = Config.from_env()
        for f in dataclasses.fields(Config):
            t = types.get(str(f.type), str)
            expected = {bool: True, int: 7, float: 2.5, str: "xyz"}[t]
            assert getattr(cfg, f.name) == expected, f.name

    def test_seed_default_flows_to_estimators(self):
        """Config.seed is the default RNG seed for estimators that do
        not set one (docs/configuration.md row); an explicit seed wins."""
        from oap_mllib_tpu.models.als import ALS
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(seed=123)
        assert KMeans().seed == 123
        assert ALS().seed == 123
        assert KMeans(seed=5).seed == 5
        assert ALS(seed=5).seed == 5

    def test_seed_default_flows_through_compat_layers(self):
        """The drop-in surfaces must honor it too (the feature is
        advertised for exactly the unmodified-user-code path): compat
        builders and the pyspark adapters resolve an unset seed from
        config at fit time."""
        from oap_mllib_tpu.compat import spark as compat_spark
        from oap_mllib_tpu.compat import pyspark as compat_pyspark

        set_config(seed=77)
        assert compat_spark.KMeans().getSeed() == 77
        assert compat_spark.ALS().getSeed() == 77
        assert compat_pyspark.KMeans().getSeed() == 77
        assert compat_pyspark.ALS().getSeed() == 77
        assert compat_spark.KMeans().setSeed(9).getSeed() == 9
        assert compat_pyspark.ALS(seed=9).getSeed() == 9

    def test_seed_default_changes_random_init(self, rng):
        """The wired seed actually reaches the RNG: two config seeds give
        different random-init clusterings of ambiguous data."""
        from oap_mllib_tpu.models.kmeans import KMeans

        x = rng.normal(size=(200, 4)).astype(np.float32)
        set_config(seed=1)
        m1 = KMeans(k=8, init_mode="random", max_iter=0).fit(x)
        set_config(seed=2)
        m2 = KMeans(k=8, init_mode="random", max_iter=0).fit(x)
        set_config(seed=1)
        m3 = KMeans(k=8, init_mode="random", max_iter=0).fit(x)
        assert not np.allclose(m1.cluster_centers_, m2.cluster_centers_)
        np.testing.assert_allclose(m1.cluster_centers_, m3.cluster_centers_)

    def test_unknown_field_rejected(self):
        with pytest.raises(ValueError, match="unknown config field"):
            set_config(sead=1)

    def test_shape_bucketing_typo_raises_at_fit(self, rng):
        """The kmeans_kernel/als_kernel contract: a typo'd knob must
        raise, not silently disable compile amortization."""
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(shape_bucketing="bogus")
        x = rng.normal(size=(64, 4)).astype(np.float32)
        with pytest.raises(ValueError, match="shape_bucketing"):
            KMeans(k=2, init_mode="random", max_iter=1).fit(x)

    def test_shape_bucketing_accepted_values(self):
        from oap_mllib_tpu.data.bucketing import bucket_factor

        assert bucket_factor("on") == 2.0
        assert bucket_factor("x2") == 2.0
        assert bucket_factor("off") is None
        assert bucket_factor("1.5") == 1.5

    def test_fault_spec_typo_raises(self):
        """A typo'd fault_spec must raise naming the valid sites — a spec
        that silently injects nothing defeats the point of fault gates
        (the kmeans_kernel/als_kernel/shape_bucketing contract)."""
        from oap_mllib_tpu.utils import faults

        set_config(fault_spec="stream.reed:fail=2")
        with pytest.raises(ValueError, match="stream.read"):
            faults.maybe_fault("stream.read")
        set_config(fault_spec="stream.read:boom=2")
        with pytest.raises(ValueError, match="kind"):
            faults.maybe_fault("stream.read")
        set_config(fault_spec="garbage")
        with pytest.raises(ValueError, match="site:kind=count"):
            faults.maybe_fault("stream.read")

    def test_nonfinite_policy_typo_raises_at_fit(self, rng):
        """The same contract for nonfinite_policy: a typo raises at the
        first streamed guardrail, not silently behaving like 'raise'."""
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(nonfinite_policy="bogus")
        x = rng.normal(size=(128, 4)).astype(np.float32)
        src = ChunkSource.from_array(x, chunk_rows=64)
        with pytest.raises(ValueError, match="nonfinite_policy"):
            KMeans(k=2, init_mode="random", max_iter=1).fit(src)

    def test_pca_kernel_typo_raises_at_fit(self, rng):
        """The kmeans_kernel contract for the PCA Gram kernel knob
        (ISSUE 9): a typo raises at fit entry, not silently keeping the
        XLA pass."""
        from oap_mllib_tpu.models.pca import PCA

        set_config(pca_kernel="bogus")
        x = rng.normal(size=(64, 4)).astype(np.float32)
        with pytest.raises(ValueError, match="pca_kernel"):
            PCA(k=2).fit(x)

    def test_als_solve_kernel_typo_raises_at_fit(self, rng):
        """Same contract for the ALS solve-kernel knob (ISSUE 9): the
        resolver runs at every runner entry."""
        from oap_mllib_tpu.models.als import ALS

        set_config(als_solve_kernel="bogus")
        u = rng.integers(0, 20, 100)
        i = rng.integers(0, 15, 100)
        r = (rng.random(100) * 4 + 1).astype(np.float32)
        with pytest.raises(ValueError, match="als_solve_kernel"):
            ALS(rank=4, max_iter=1).fit(u, i, r)

    def test_ring_reduction_typo_raises_at_fit(self, rng):
        """Same contract for the ring knob (ISSUE 9): validated on every
        accelerated K-Means dispatch, single-device included."""
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(ring_reduction="ring")
        x = rng.normal(size=(64, 4)).astype(np.float32)
        with pytest.raises(ValueError, match="ring_reduction"):
            KMeans(k=2, init_mode="random", max_iter=1).fit(x)

    def test_compute_precision_typo_raises_at_fit(self, rng):
        """The kmeans_kernel/als_kernel contract for the precision
        policy: a typo'd tier must raise at fit entry, not silently run
        f32."""
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(compute_precision="bf8")
        x = rng.normal(size=(64, 4)).astype(np.float32)
        with pytest.raises(ValueError, match="compute_precision"):
            KMeans(k=2, init_mode="random", max_iter=1).fit(x)

    def test_per_algo_precision_overrides_inherit_and_validate(self):
        from oap_mllib_tpu.utils import precision as psn

        set_config(compute_precision="tf32")
        # empty overrides inherit the global policy
        assert psn.resolve("kmeans").name == "tf32"
        assert psn.resolve("pca").name == "tf32"
        set_config(pca_precision="f32")
        assert psn.resolve("pca").name == "f32"
        assert psn.resolve("als").name == "tf32"
        set_config(kmeans_precision="nope")
        with pytest.raises(ValueError, match="kmeans_precision"):
            psn.resolve("kmeans")

    def test_collective_timeout_negative_raises_at_dispatch(self):
        """The kmeans_kernel/fault_spec contract for the recovery plane:
        a nonsense deadline raises at the dispatch seam, not silently
        disarming the watchdog (utils/recovery.py)."""
        from oap_mllib_tpu.utils import recovery

        set_config(collective_timeout=-1.0)
        with pytest.raises(ValueError, match="collective_timeout"):
            recovery.guarded_dispatch("psum", "data", lambda: 1)

    def test_chaos_typo_raises(self):
        """A malformed chaos spec must raise naming the grammar — a
        chaos drill that silently injects nothing proves nothing."""
        from oap_mllib_tpu.utils import faults

        set_config(chaos="garbage")
        with pytest.raises(ValueError, match="seed:rate"):
            faults.maybe_fault("stream.read")
        set_config(chaos="7:0.1:boom")
        with pytest.raises(ValueError, match="kind"):
            faults.maybe_fault("stream.read")

    def test_fleet_stats_typo_raises_at_pass(self, rng):
        """The kmeans_kernel contract for the fleet plane (ISSUE 11): a
        typo'd mode raises at the first streamed pass, not silently
        disarming the rollups."""
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(fleet_stats="always")
        x = rng.normal(size=(200, 4)).astype(np.float32)

        def gen():
            for lo in range(0, 200, 100):
                yield x[lo:lo + 100]

        src = ChunkSource(gen, 4, 100, n_rows=200)
        with pytest.raises(ValueError, match="fleet_stats"):
            KMeans(k=2, init_mode="random", max_iter=1).fit(src)

    def test_metrics_port_negative_raises(self):
        from oap_mllib_tpu.telemetry import fleet

        set_config(metrics_port=-5)
        with pytest.raises(ValueError, match="metrics_port"):
            fleet.maybe_serve()

    def test_flight_recorder_negative_raises(self):
        from oap_mllib_tpu.telemetry import flightrec

        set_config(flight_recorder=-3)
        with pytest.raises(ValueError, match="flight_recorder"):
            flightrec.record("span_open", "x")

    def test_capability_sharding_typo_raises(self):
        """The kmeans_kernel contract for the balance plane (ISSUE 15):
        a typo'd mode raises at the armed() check, not silently keeping
        equal shards."""
        from oap_mllib_tpu.parallel import balance

        set_config(capability_sharding="weighted")
        with pytest.raises(ValueError, match="capability_sharding"):
            balance.armed(2)

    def test_rank_capability_typo_raises(self):
        from oap_mllib_tpu.utils import dispatch

        set_config(rank_capability="slow")
        with pytest.raises(ValueError, match="rank_capability"):
            dispatch.pinned_capability()
        set_config(rank_capability="-1.0")
        with pytest.raises(ValueError, match="> 0"):
            dispatch.pinned_capability()

    def test_rebalance_knobs_validate(self):
        from oap_mllib_tpu.parallel import balance

        set_config(rebalance_threshold=0.9)
        with pytest.raises(ValueError, match="rebalance_threshold"):
            balance.rebalance_threshold_cfg()
        set_config(rebalance_threshold=1.5, rebalance_patience=0)
        with pytest.raises(ValueError, match="rebalance_patience"):
            balance.rebalance_patience_cfg()

    def test_supervisor_knobs_reach_supervisor(self, tmp_path):
        """restart_budget / restart_backoff / shrink_after flow into
        Supervisor defaults (utils/supervisor.py)."""
        from oap_mllib_tpu.utils.supervisor import Supervisor

        set_config(restart_budget=9, restart_backoff=0.5, shrink_after=3)
        sup = Supervisor(lambda r, w, c, a: ["true"], 1,
                         str(tmp_path / "sb"))
        assert sup.restart_budget == 9
        assert sup.restart_backoff == 0.5
        assert sup.shrink_after == 3

    def test_crash_dir_arms_the_sideband(self, tmp_path):
        from oap_mllib_tpu.utils import recovery

        set_config(crash_dir="")
        assert recovery.write_crash_record("s", "oom", "x") is None
        set_config(crash_dir=str(tmp_path))
        path = recovery.write_crash_record("s", "oom", "x")
        assert path is not None and path.startswith(str(tmp_path))

    def test_memory_budget_typo_raises_at_fit(self, rng):
        """The kmeans_kernel contract for the route planner (ISSUE 12):
        a budget that parses to nothing must raise at fit entry, not
        silently plan unbounded."""
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(memory_budget_hbm="12Q")
        x = rng.normal(size=(64, 4)).astype(np.float32)
        with pytest.raises(ValueError, match="memory budget"):
            KMeans(k=2, init_mode="random", max_iter=1).fit(x)
        set_config(memory_budget_hbm="")

    def test_budget_knobs_reach_planner(self):
        from oap_mllib_tpu.utils import membudget

        set_config(memory_budget_hbm="64M", memory_budget_host="2G")
        b = membudget.Budgets.resolve()
        assert b.hbm == 64 << 20 and b.host == 2 << 30
        assert b.hbm_source == "config" and b.host_source == "config"
        set_config(memory_budget_hbm="", memory_budget_host="")

    def test_spill_dir_reaches_spill(self, rng, tmp_path):
        import os

        from oap_mllib_tpu.data.stream import ChunkSource

        set_config(spill_dir=str(tmp_path))
        x = rng.normal(size=(100, 3)).astype(np.float32)
        spilled = ChunkSource.from_array(x, chunk_rows=64).spill_to_disk()
        np.testing.assert_array_equal(spilled.to_array(), x)
        assert any(
            f.startswith("oap-spill.") for f in os.listdir(tmp_path)
        )
        set_config(spill_dir="")

    def test_retry_knobs_reach_policy(self):
        """retry_limit / retry_backoff / retry_deadline flow into
        RetryPolicy.from_config with float coercion intact."""
        from oap_mllib_tpu.utils.resilience import RetryPolicy

        set_config(retry_limit=2, retry_backoff=0.25, retry_deadline=9.0)
        p = RetryPolicy.from_config()
        assert p.max_retries == 2
        assert p.backoff_s == 0.25
        assert p.deadline_s == 9.0

    def test_profile_dir_respects_config_overrides(self, monkeypatch,
                                                   tmp_path):
        """Config.profile_dir (the promoted OAP_MLLIB_TPU_PROFILE_DIR)
        drives utils/profiling.maybe_trace through the config layer, so
        set_config/scoped overrides work — not just the raw env var."""
        from oap_mllib_tpu.utils import profiling

        traced = []

        @__import__("contextlib").contextmanager
        def fake_trace(log_dir):
            traced.append(log_dir)
            yield

        monkeypatch.setattr(profiling, "trace", fake_trace)
        with profiling.maybe_trace():
            pass
        assert traced == []  # default: off
        set_config(profile_dir=str(tmp_path))
        with profiling.maybe_trace():
            pass
        assert traced == [str(tmp_path)]

    def test_profile_dir_env_coerced(self, monkeypatch):
        """The env var now flows through the standard coercion like
        every other knob."""
        monkeypatch.setenv("OAP_MLLIB_TPU_PROFILE_DIR", "/tmp/x")
        assert Config.from_env().profile_dir == "/tmp/x"

    def test_telemetry_log_arms_the_jsonl_sink(self, tmp_path):
        from oap_mllib_tpu.telemetry.export import sink_path

        assert sink_path() is None  # default: off
        set_config(telemetry_log=str(tmp_path / "t.jsonl"))
        assert sink_path() == str(tmp_path / "t.jsonl")

    def test_compilation_cache_dir_wires_jax_config(self, tmp_path):
        """Config.compilation_cache_dir reaches jax's persistent cache
        at dispatch time (the every-fit chokepoint)."""
        import jax

        from oap_mllib_tpu.utils import progcache
        from oap_mllib_tpu.utils.dispatch import should_accelerate

        prev_dir = jax.config.jax_compilation_cache_dir
        prev_applied = progcache._persist_applied
        try:
            cache_dir = str(tmp_path / "xla")
            set_config(compilation_cache_dir=cache_dir)
            should_accelerate("PCA", True)
            assert jax.config.jax_compilation_cache_dir == cache_dir
        finally:
            jax.config.update("jax_compilation_cache_dir", prev_dir)
            progcache._persist_applied = prev_applied
