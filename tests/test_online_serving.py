"""In-place serving re-pin (ISSUE 20): delta commits bump the served
model version and refresh the device pins WITHOUT evicting the handle.

Contracts under test:

- a committed delta re-pins every handle bound to the model: version
  bumps, the staleness clock resets, and the SAME handle object
  answers through the new state (bit-identical to a direct model
  call);
- a FAILED delta commit leaves the pin untouched — the handle keeps
  answering bit-identically through the old version (the compute-then
  -swap regression);
- ``online_repin="off"`` freezes the pin until an explicit
  ``repin_model``;
- serving_summary()/serving_health_block() expose per-handle
  ``model_version`` + ``staleness_seconds``.
"""

from __future__ import annotations

import numpy as np
import pytest

from oap_mllib_tpu import serving
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.models.als import ALS
from oap_mllib_tpu.models.kmeans import KMeans
from oap_mllib_tpu.online import IncrementalPCA
from oap_mllib_tpu.serving import registry
from oap_mllib_tpu.telemetry import metrics as tm
from oap_mllib_tpu.utils.faults import FaultInjected


@pytest.fixture(autouse=True)
def _clear_registry():
    registry.clear()
    yield
    registry.clear()


@pytest.fixture
def rng():
    return np.random.default_rng(21)


class TestRepinOnCommit:
    def test_kmeans_partial_fit_repins_served_handle(self, rng):
        x = rng.normal(size=(500, 6)).astype(np.float32)
        m = KMeans(k=3, seed=1, max_iter=5).fit(x)
        h = serving.serve(m)
        assert h.model_version == 1
        q = rng.normal(size=(40, 6)).astype(np.float32)
        h.predict(q)  # warm the old pin
        m.partial_fit(rng.normal(size=(200, 6)).astype(np.float32) + 2.0)
        assert h.model_version == 2
        # the SAME handle answers through the NEW centers, exactly
        np.testing.assert_array_equal(h.predict(q), m.predict(q))
        assert serving.serve(m) is h  # never evicted, never re-keyed

    def test_staleness_resets_on_commit(self, rng):
        x = rng.normal(size=(300, 4)).astype(np.float32)
        m = KMeans(k=2, seed=1, max_iter=4).fit(x)
        h = serving.serve(m)
        h._committed_at -= 100.0  # age the pin
        assert h.staleness_seconds() > 99
        m.partial_fit(x[:50])
        assert h.staleness_seconds() < 5
        assert (
            tm.gauge(
                "oap_serve_model_staleness_seconds", {"model": "kmeans"}
            ).value < 5
        )
        assert (
            tm.gauge("oap_serve_model_version", {"model": "kmeans"}).value
            == 2
        )

    def test_ipca_commit_repins_same_handle(self, rng):
        x = rng.normal(size=(400, 5)).astype(np.float32)
        ip = IncrementalPCA(2)
        ip.partial_fit(x[:200])
        m = ip.commit()
        h = serving.serve(m)
        q = rng.normal(size=(30, 5)).astype(np.float32)
        h.transform(q)
        ip.partial_fit(x[200:] + 1.5)
        ip.commit()
        assert h.model_version == 2
        np.testing.assert_array_equal(h.transform(q), m.transform(q))

    def test_als_foldin_repins_and_serves_grown_table(self, rng):
        u = rng.integers(0, 30, size=1500)
        i = rng.integers(0, 25, size=1500)
        r = rng.normal(1.0, 0.5, size=1500).astype(np.float32)
        m = ALS(rank=3, max_iter=4, reg_param=0.1, seed=2).fit(
            u, i, r, n_users=30, n_items=25
        )
        h = serving.serve(m)
        ids_before = m.recommend_for_users(np.arange(5), 3)
        out = m.fold_in_users(
            np.full(6, 34), np.arange(6),
            rng.normal(1.0, 0.5, size=6).astype(np.float32),
        )
        assert out["repinned"] == 1 and h.model_version == 2
        # the grown user serves top-k through the frozen item table
        ids = m.recommend_for_users([34], 4)
        assert ids.shape == (1, 4)
        # untouched users still answer (and the old rows were untouched)
        np.testing.assert_array_equal(
            m.recommend_for_users(np.arange(5), 3), ids_before
        )

    def test_repin_off_freezes_pin_until_explicit(self, rng):
        set_config(online_repin="off")
        x = rng.normal(size=(300, 4)).astype(np.float32)
        m = KMeans(k=2, seed=1, max_iter=4).fit(x)
        h = serving.serve(m)
        old_centers = h.centers_dev
        m.partial_fit(x + 3.0)
        assert h.model_version == 1
        assert h.centers_dev is old_centers  # still the old pin
        assert registry.repin_model(m) == 1  # the explicit operator path
        assert h.model_version == 2
        assert h.centers_dev is not old_centers

    def test_repin_typo_raises(self, rng):
        set_config(online_repin="eager")
        m = KMeans(k=2, seed=1, max_iter=3).fit(
            rng.normal(size=(200, 3)).astype(np.float32)
        )
        with pytest.raises(ValueError, match="online_repin"):
            m.partial_fit(rng.normal(size=(50, 3)).astype(np.float32))

    def test_repin_model_unserved_is_zero(self, rng):
        m = KMeans(k=2, seed=1, max_iter=3).fit(
            rng.normal(size=(200, 3)).astype(np.float32)
        )
        assert registry.repin_model(m) == 0

    def test_books_repin_counter(self, rng):
        before = tm.family_total("oap_serve_repins_total")
        x = rng.normal(size=(200, 3)).astype(np.float32)
        m = KMeans(k=2, seed=1, max_iter=3).fit(x)
        serving.serve(m)
        m.partial_fit(x[:50])
        assert tm.family_total("oap_serve_repins_total") == before + 1


class TestFailedCommitLeavesPinServing:
    def test_kmeans_fault_keeps_old_answers_bit_identical(self, rng):
        x = rng.normal(size=(400, 5)).astype(np.float32)
        m = KMeans(k=3, seed=1, max_iter=5).fit(x)
        h = serving.serve(m)
        q = rng.normal(size=(60, 5)).astype(np.float32)
        before = h.predict(q)
        set_config(fault_spec="delta.ingest:err=1")
        with pytest.raises(FaultInjected):
            m.partial_fit(x + 5.0)
        assert h.model_version == 1
        np.testing.assert_array_equal(h.predict(q), before)

    def test_als_solve_fault_keeps_old_pin(self, rng):
        u = rng.integers(0, 25, size=1200)
        i = rng.integers(0, 20, size=1200)
        r = rng.normal(1.0, 0.5, size=1200).astype(np.float32)
        m = ALS(rank=3, max_iter=4, reg_param=0.1, seed=2).fit(
            u, i, r, n_users=25, n_items=20
        )
        h = serving.serve(m)
        before = m.recommend_for_users(np.arange(6), 3)
        set_config(fault_spec="delta.solve:err=1")
        with pytest.raises(FaultInjected):
            m.fold_in_users([30, 30], [0, 1], [1.0, 2.0])
        assert h.model_version == 1
        assert m.user_factors_.shape == (25, 3)
        np.testing.assert_array_equal(
            m.recommend_for_users(np.arange(6), 3), before
        )


class TestObservabilitySurfaces:
    def test_serving_summary_models_block(self, rng):
        x = rng.normal(size=(200, 4)).astype(np.float32)
        m = KMeans(k=2, seed=1, max_iter=3).fit(x)
        serving.serve(m)
        m.partial_fit(x[:40])
        block = registry.serving_summary()
        models = {b["kind"]: b for b in block["models"]}
        assert models["kmeans"]["model_version"] == 2
        assert models["kmeans"]["staleness_seconds"] < 60

    def test_health_block_carries_versions(self, rng):
        from oap_mllib_tpu.serving import traffic

        x = rng.normal(size=(200, 4)).astype(np.float32)
        m = KMeans(k=2, seed=1, max_iter=3).fit(x)
        serving.serve(m)
        out = traffic.serving_health_block()
        kinds = {b["kind"] for b in out["models"]}
        assert "kmeans" in kinds
        assert all("model_version" in b for b in out["models"])

    def test_handle_stats_carry_version(self, rng):
        x = rng.normal(size=(200, 4)).astype(np.float32)
        m = KMeans(k=2, seed=1, max_iter=3).fit(x)
        h = serving.serve(m)
        s = h.stats()
        assert s["model_version"] == 1
        assert s["staleness_seconds"] >= 0
