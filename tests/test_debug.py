"""Debug printer tests (Service.java printNumericTable analogs)."""

import numpy as np

from oap_mllib_tpu.data.table import CSRTable
from oap_mllib_tpu.utils.debug import format_csr, format_table


class TestFormatTable:
    def test_dense_head_and_shape(self, rng):
        x = rng.normal(size=(100, 5))
        out = format_table(x, "features", max_rows=3)
        assert out.splitlines()[0] == "features (100 x 5)"
        assert len(out.splitlines()) == 5  # title + 3 rows + ellipsis
        assert "more rows" in out

    def test_1d_and_col_truncation(self, rng):
        out = format_table(np.arange(4.0), "v")
        assert "(4 x 1)" in out
        wide = format_table(rng.normal(size=(2, 30)), max_cols=4)
        # truncation note lives on its own summary line, not glued to data
        assert wide.splitlines()[-1] == "  ... (26 more cols)"
        both = format_table(rng.normal(size=(9, 30)), max_rows=2, max_cols=4)
        assert both.splitlines()[-1] == "  ... (7 more rows, 26 more cols)"

    def test_sharded_device_table(self, rng):
        import jax

        from oap_mllib_tpu.parallel.mesh import get_mesh, shard_rows

        x = rng.normal(size=(64, 4)).astype(np.float32)
        data = shard_rows(x, get_mesh())
        assert isinstance(data, jax.Array)
        out = format_table(data, "sharded", max_rows=2)
        assert "(64 x 4)" in out
        # printed head matches the host rows
        assert f"{x[0, 0]: .6f}".strip() in out


class TestFormatCsr:
    def test_rows_and_pairs(self):
        t = CSRTable.from_coo(
            np.array([0, 0, 2]), np.array([1, 3, 0]),
            np.array([1.5, 2.5, 3.5], np.float32), n_rows=3, n_cols=4,
        )
        out = format_csr(t, "ratings")
        lines = out.splitlines()
        assert "ratings (3 x 4, nnz=3)" == lines[0]
        assert lines[1].startswith("  [0]") and "1:1.5000" in lines[1]
        assert lines[2] == "  [1] "  # empty row
        assert "0:3.5000" in lines[3]

    def test_precision_threads_through_like_format_table(self):
        """format_csr honors a precision arg for its values exactly like
        format_table does (default keeps the historical 4 decimals)."""
        t = CSRTable.from_coo(
            np.array([0]), np.array([2]),
            np.array([1.23456789], np.float32), n_rows=1, n_cols=3,
        )
        assert "2:1.2346" in format_csr(t)  # default unchanged
        assert "2:1.23" in format_csr(t, precision=2)
        assert "2:1.234568" in format_csr(t, precision=6)
