"""Pipeline / ParamGridBuilder / CrossValidator composability tests
(compat/pipeline.py — the ml.Pipeline / ml.tuning analog the round-3
review flagged as missing from the dict world)."""

import numpy as np
import pytest

from oap_mllib_tpu.compat import (
    ALS,
    CrossValidator,
    KMeans,
    PCA,
    ParamGridBuilder,
    Pipeline,
    RegressionEvaluator,
    TrainValidationSplit,
)


def _blobs(rng, n=300, d=6, k=3):
    proto = rng.normal(size=(k, d)) * 8
    x = proto[rng.integers(k, size=n)] + 0.1 * rng.normal(size=(n, d))
    return {"features": x.astype(np.float64)}


def _ratings(rng, n=1500, nu=40, ni=30, rank=3):
    u = rng.integers(0, nu, n)
    i = rng.integers(0, ni, n)
    xt = rng.normal(size=(nu, rank))
    yt = rng.normal(size=(ni, rank))
    r = (xt[u] * yt[i]).sum(1) + 0.05 * rng.normal(size=n)
    return {"user": u, "item": i,
            "rating": r.astype(np.float32)}


class TestPipeline:
    def test_pca_then_kmeans(self, rng):
        """Classic two-stage flow: PCA features feed K-Means — the
        second stage must fit on the FIRST stage's transformed frame."""
        df = _blobs(rng, d=8)
        pipe = Pipeline(stages=[
            PCA().setK(3).setInputCol("features").setOutputCol("pca"),
            KMeans().setK(3).setSeed(1).setFeaturesCol("pca"),
        ])
        model = pipe.fit(df)
        out = model.transform(df)
        assert out["pca"].shape == (300, 3)
        assert set(np.unique(out["prediction"])) <= {0, 1, 2}
        # blobs survive the projection: near-pure clusters
        assert len(np.unique(out["prediction"])) == 3

    def test_transformer_stage_passthrough(self, rng):
        """A fitted model used as a stage passes through (no fit call)."""
        df = _blobs(rng)
        km = KMeans().setK(3).setSeed(1).fit(df)
        model = Pipeline(stages=[km]).fit(df)
        out = model.transform(df)
        assert "prediction" in out

    def test_bad_stage_raises(self, rng):
        with pytest.raises(TypeError, match="neither fit nor transform"):
            Pipeline(stages=[object()]).fit(_blobs(rng))

    def test_stages_accessors(self):
        p = Pipeline().setStages([1, 2])
        assert p.getStages() == [1, 2]


class TestParamGrid:
    def test_cartesian_build(self):
        grid = (ParamGridBuilder()
                .addGrid("regParam", [0.01, 0.1])
                .addGrid("rank", [2, 4, 8])
                .baseOn({"maxIter": 3})
                .build())
        assert len(grid) == 6
        assert all(m["maxIter"] == 3 for m in grid)
        assert {m["regParam"] for m in grid} == {0.01, 0.1}

    def test_empty_grid_is_one_default_map(self):
        assert ParamGridBuilder().build() == [{}]


class TestCrossValidator:
    def test_als_reg_param_selection(self, rng):
        """The canonical Spark tuning flow: ALS regParam grid, RMSE
        evaluator (smaller better) — CV must prefer a sane reg over an
        absurd one and expose per-map metrics."""
        df = _ratings(rng)
        cv = CrossValidator(
            estimator=(ALS().setRank(4).setMaxIter(4)
                       .setColdStartStrategy("drop")),
            estimatorParamMaps=(ParamGridBuilder()
                                .addGrid("regParam", [0.05, 50.0])
                                .build()),
            evaluator=RegressionEvaluator(metricName="rmse",
                                          labelCol="rating"),
            numFolds=3, seed=1,
        )
        model = cv.fit(df)
        assert len(model.avgMetrics) == 2
        assert model.bestParams == {"regParam": 0.05}
        assert model.avgMetrics[0] < model.avgMetrics[1]
        out = model.transform(df)
        assert np.isfinite(out["prediction"]).all()

    def test_larger_is_better_direction(self, rng):
        """r2 (larger better) must flip the argbest direction."""
        df = _ratings(rng)
        cv = CrossValidator(
            estimator=(ALS().setRank(4).setMaxIter(4)
                       .setColdStartStrategy("drop")),
            estimatorParamMaps=(ParamGridBuilder()
                                .addGrid("regParam", [0.05, 50.0])
                                .build()),
            evaluator=RegressionEvaluator(metricName="r2",
                                          labelCol="rating"),
            numFolds=2, seed=1,
        )
        model = cv.fit(df)
        assert model.bestParams == {"regParam": 0.05}

    def test_unknown_param_fails_before_any_fit(self, rng):
        cv = CrossValidator(
            estimator=ALS(),
            estimatorParamMaps=[{"regParm": 0.1}],  # typo
            evaluator=RegressionEvaluator(labelCol="rating"),
        )
        with pytest.raises(ValueError, match="regParm"):
            cv.fit(_ratings(rng))

    def test_nan_metric_raises_not_wins(self, rng):
        """coldStartStrategy="nan" leaks NaN predictions into RMSE; the
        NaN map must raise, not silently win argmin."""
        df = _ratings(rng, nu=15, ni=12)
        # a user with exactly ONE rating: whichever fold holds it tests
        # an id unseen in that fold's training -> NaN prediction
        df = {k: np.asarray(v).copy() for k, v in df.items()}
        df["user"][0] = 999
        cv = CrossValidator(
            estimator=ALS().setRank(3).setMaxIter(2),  # default "nan"
            estimatorParamMaps=(ParamGridBuilder()
                                .addGrid("regParam", [0.05, 0.5]).build()),
            evaluator=RegressionEvaluator(labelCol="rating"),
            numFolds=5, seed=0,
        )
        with pytest.raises(ValueError, match="NaN"):
            cv.fit(df)

    def test_empty_grid_raises(self, rng):
        cv = CrossValidator(
            estimator=ALS().setColdStartStrategy("drop"),
            estimatorParamMaps=(ParamGridBuilder()
                                .addGrid("regParam", []).build()),
            evaluator=RegressionEvaluator(labelCol="rating"),
        )
        with pytest.raises(ValueError, match="empty"):
            cv.fit(_ratings(rng))

    def test_validation(self, rng):
        with pytest.raises(ValueError, match="estimator and evaluator"):
            CrossValidator().fit(_ratings(rng))
        with pytest.raises(ValueError, match="numFolds"):
            CrossValidator(
                estimator=ALS(),
                evaluator=RegressionEvaluator(labelCol="rating"),
                numFolds=1,
            ).fit(_ratings(rng))
        with pytest.raises(TypeError, match="dict DataFrame"):
            CrossValidator(
                estimator=ALS(),
                evaluator=RegressionEvaluator(labelCol="rating"),
            ).fit(np.zeros((10, 3)))


class TestTrainValidationSplit:
    def test_selects_sane_reg(self, rng):
        df = _ratings(rng)
        tvs = TrainValidationSplit(
            estimator=(ALS().setRank(4).setMaxIter(4)
                       .setColdStartStrategy("drop")),
            estimatorParamMaps=(ParamGridBuilder()
                                .addGrid("regParam", [0.05, 50.0])
                                .build()),
            evaluator=RegressionEvaluator(metricName="rmse",
                                          labelCol="rating"),
            trainRatio=0.8, seed=1,
        )
        model = tvs.fit(df)
        assert model.bestParams == {"regParam": 0.05}
        assert len(model.validationMetrics) == 2
        assert model.validationMetrics[0] < model.validationMetrics[1]
        out = model.transform(df)
        assert np.isfinite(out["prediction"]).all()

    def test_train_ratio_validation(self, rng):
        tvs = TrainValidationSplit(
            estimator=ALS().setColdStartStrategy("drop"),
            evaluator=RegressionEvaluator(labelCol="rating"),
            trainRatio=1.0,
        )
        with pytest.raises(ValueError, match="trainRatio"):
            tvs.fit(_ratings(rng))


class TestPersistence:
    """save/load for the composability containers (Spark MLWritable
    analog — the reference inherits pipeline/tuner persistence from
    Spark for free, e.g. IntelPCASuite.scala:90-104)."""

    def test_pipeline_model_roundtrip(self, rng, tmp_path):
        from oap_mllib_tpu.compat.pipeline import PipelineModel

        df = _blobs(rng, d=8)
        model = Pipeline(stages=[
            PCA().setK(3).setInputCol("features").setOutputCol("pca"),
            KMeans().setK(3).setSeed(1).setFeaturesCol("pca"),
        ]).fit(df)
        model.save(str(tmp_path / "pm"))
        loaded = PipelineModel.load(str(tmp_path / "pm"))
        a, b = model.transform(df), loaded.transform(df)
        np.testing.assert_allclose(a["pca"], b["pca"], atol=1e-6)
        np.testing.assert_array_equal(a["prediction"], b["prediction"])

    def test_unfitted_pipeline_roundtrip(self, rng, tmp_path):
        pipe = Pipeline(stages=[
            PCA().setK(2).setInputCol("features").setOutputCol("pca"),
            KMeans().setK(3).setSeed(7).setFeaturesCol("pca"),
        ])
        pipe.save(str(tmp_path / "p"))
        loaded = Pipeline.load(str(tmp_path / "p"))
        stages = loaded.getStages()
        assert stages[0].getK() == 2 and stages[0].getOutputCol() == "pca"
        assert stages[1].getK() == 3 and stages[1].getSeed() == 7
        # a loaded estimator pipeline must still FIT
        df = _blobs(rng)
        out = loaded.fit(df).transform(df)
        assert out["pca"].shape[1] == 2

    def test_cv_model_roundtrip_cold_start(self, rng, tmp_path):
        """A loaded CrossValidatorModel keeps metrics/params AND its ALS
        stage's coldStartStrategy (drop must still remove unseen ids)."""
        from oap_mllib_tpu.compat.pipeline import CrossValidatorModel

        df = _ratings(rng)
        cv = CrossValidator(
            estimator=(ALS().setRank(3).setMaxIter(3)
                       .setColdStartStrategy("drop")),
            estimatorParamMaps=(ParamGridBuilder()
                                .addGrid("regParam", [0.05, 50.0])
                                .build()),
            evaluator=RegressionEvaluator(metricName="rmse",
                                          labelCol="rating"),
            numFolds=2, seed=1,
        )
        model = cv.fit(df)
        model.save(str(tmp_path / "cv"))
        loaded = CrossValidatorModel.load(str(tmp_path / "cv"))
        assert loaded.bestParams == model.bestParams
        np.testing.assert_allclose(loaded.avgMetrics, model.avgMetrics)
        probe = {"user": np.array([0, 999]), "item": np.array([0, 1]),
                 "rating": np.array([1.0, 2.0], np.float32)}
        out = loaded.transform(probe)
        assert len(out["prediction"]) == 1  # unseen user still dropped
        assert np.isfinite(out["prediction"]).all()

    def test_tvs_model_roundtrip(self, rng, tmp_path):
        from oap_mllib_tpu.compat.pipeline import TrainValidationSplitModel

        df = _ratings(rng)
        model = TrainValidationSplit(
            estimator=(ALS().setRank(3).setMaxIter(3)
                       .setColdStartStrategy("drop")),
            estimatorParamMaps=(ParamGridBuilder()
                                .addGrid("regParam", [0.05, 50.0])
                                .build()),
            evaluator=RegressionEvaluator(metricName="rmse",
                                          labelCol="rating"),
            trainRatio=0.8, seed=1,
        ).fit(df)
        model.save(str(tmp_path / "tvs"))
        loaded = TrainValidationSplitModel.load(str(tmp_path / "tvs"))
        assert loaded.bestParams == model.bestParams
        np.testing.assert_allclose(loaded.validationMetrics,
                                   model.validationMetrics)
        a, b = model.transform(df), loaded.transform(df)
        np.testing.assert_allclose(a["prediction"], b["prediction"],
                                   atol=1e-6)

    def test_manifest_type_mismatch_raises(self, rng, tmp_path):
        from oap_mllib_tpu.compat.pipeline import CrossValidatorModel

        df = _blobs(rng)
        Pipeline(stages=[KMeans().setK(2).setSeed(1)]).fit(df).save(
            str(tmp_path / "pm")
        )
        with pytest.raises(ValueError, match="not a CrossValidatorModel"):
            CrossValidatorModel.load(str(tmp_path / "pm"))

    def test_manifest_foreign_module_refused(self, tmp_path):
        """A tampered manifest must not import arbitrary classes."""
        import json
        import os

        from oap_mllib_tpu.compat.pipeline import PipelineModel

        d = tmp_path / "evil"
        os.makedirs(d / "stage_00_X")
        with open(d / "pipeline_metadata.json", "w") as f:
            json.dump({"type": "PipelineModel", "version": 1,
                       "stages": [{"dir": "stage_00_X",
                                   "module": "os", "cls": "system"}]}, f)
        with pytest.raises(ValueError, match="refusing"):
            PipelineModel.load(str(d))
