"""Prefetch pipeline unit tests (data/prefetch.py).

The contracts under test, in the module's own order: chunk order and math
are depth-invariant (depth=1 parity with the serial path), staging of
chunk N+1 really overlaps compute of chunk N at depth >= 2 (a concurrency
COUNTER, not wall-clock totals — the tier-1 suite must stay
deterministic), the producer never runs more than ``depth`` chunks ahead
(bounded backpressure), staging errors re-raise at the consumer with
their original type/message (the _PassGuard fail-fast contract upstream),
and an early consumer exit shuts the producer down instead of stranding
it.
"""

import threading
import time

import numpy as np
import pytest

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.data.prefetch import Prefetcher, PrefetchStats, resolve_depth
from oap_mllib_tpu.data.stream import ChunkSource


class TestDepthResolution:
    def test_config_default_and_override(self, monkeypatch):
        # dev/ci.sh runs this file under forced env depths; the default
        # under test is the dataclass one
        monkeypatch.delenv("OAP_MLLIB_TPU_PREFETCH_DEPTH", raising=False)
        assert resolve_depth() == 2  # Config.prefetch_depth default
        set_config(prefetch_depth=5)
        assert resolve_depth() == 5
        assert resolve_depth(3) == 3  # explicit beats config

    def test_depth_below_one_rejected(self):
        with pytest.raises(ValueError, match="depth"):
            resolve_depth(0)


class TestOrderAndParity:
    def test_order_preserved_every_depth(self):
        items = list(range(57))
        for depth in (1, 2, 4, 8):
            with Prefetcher(items, stage=lambda v: v * 10, depth=depth) as pf:
                assert list(pf) == [v * 10 for v in items]

    def test_depth1_is_inline_serial(self):
        """depth=1 must run the stage on the CONSUMER thread on demand —
        the bit-identical pre-pipeline loop, no thread."""
        main = threading.get_ident()
        seen = []
        with Prefetcher(
            range(5), stage=lambda v: seen.append(threading.get_ident()) or v,
            depth=1,
        ) as pf:
            out = list(pf)
        assert out == list(range(5))
        assert set(seen) == {main}

    def test_depth2_stages_off_thread(self):
        main = threading.get_ident()
        seen = []
        with Prefetcher(
            range(5), stage=lambda v: seen.append(threading.get_ident()) or v,
            depth=2,
        ) as pf:
            list(pf)
        assert main not in set(seen)

    def test_streamed_lloyd_depth_invariant(self, rng):
        """The real consumer: streamed Lloyd produces bit-identical
        centers/cost at depth 1 (serial) and depth 3 (pipelined) — depth
        moves WHEN staging happens, never the math."""
        from oap_mllib_tpu.ops import stream_ops

        x = rng.normal(size=(700, 9)).astype(np.float32)
        init = x[rng.choice(700, 4, replace=False)]
        results = []
        for depth in (1, 3):
            set_config(prefetch_depth=depth)
            src = ChunkSource.from_array(x, chunk_rows=128)
            results.append(stream_ops.lloyd_run_streamed(
                src, init, 10, 1e-6, np.float32
            ))
        (c1, i1, t1, n1), (c3, i3, t3, n3) = results
        assert int(i1) == int(i3)
        np.testing.assert_array_equal(np.asarray(c1), np.asarray(c3))
        np.testing.assert_array_equal(float(t1), float(t3))
        np.testing.assert_array_equal(np.asarray(n1), np.asarray(n3))

    def test_streamed_covariance_depth_invariant(self, rng):
        from oap_mllib_tpu.ops import stream_ops

        x = rng.normal(size=(400, 7)).astype(np.float32) + 2.0
        outs = []
        for depth in (1, 4):
            set_config(prefetch_depth=depth)
            src = ChunkSource.from_array(x, chunk_rows=96)
            outs.append(stream_ops.covariance_streamed(src, np.float32))
        np.testing.assert_array_equal(outs[0][0], outs[1][0])
        np.testing.assert_array_equal(outs[0][1], outs[1][1])
        assert outs[0][2] == outs[1][2]


class _OverlapProbe:
    """Shared state for the concurrency-counter tests: the source's
    generator records whether the consumer was mid-compute when the
    producer pulled each chunk."""

    def __init__(self, n_chunks: int, chunk_rows: int = 8, d: int = 3,
                 pull_sleep: float = 0.02):
        self.in_compute = threading.Event()
        self.overlaps = 0
        self.pulled = 0
        self.consumed = 0
        self.max_lead = 0
        self.n_chunks = n_chunks
        self.chunk_rows = chunk_rows
        self.d = d
        self.pull_sleep = pull_sleep

    def gen(self):
        for i in range(self.n_chunks):
            time.sleep(self.pull_sleep)  # a "slow" source (file IO analog)
            if self.in_compute.is_set():
                self.overlaps += 1
            self.pulled += 1
            self.max_lead = max(self.max_lead, self.pulled - self.consumed)
            yield np.full((self.chunk_rows, self.d), float(i), np.float32)

    def source(self) -> ChunkSource:
        return ChunkSource(
            self.gen, n_features=self.d, chunk_rows=self.chunk_rows
        )

    def compute(self, seconds: float = 0.05):
        self.in_compute.set()
        time.sleep(seconds)
        self.in_compute.clear()
        self.consumed += 1


class TestOverlapAndBackpressure:
    def test_staging_overlaps_compute_at_depth2(self):
        """The tentpole claim, proven by counter: at depth >= 2 the
        producer pulls chunk N+1 WHILE the consumer computes chunk N."""
        probe = _OverlapProbe(n_chunks=6)
        with Prefetcher(probe.source(), depth=2) as pf:
            for _ in pf:
                probe.compute()
        assert probe.pulled == probe.n_chunks
        assert probe.overlaps >= 2, (
            f"no staging happened during compute (overlaps="
            f"{probe.overlaps}) — the pipeline is serial"
        )

    def test_depth1_never_overlaps(self):
        """depth=1 is the serial baseline: the source is only ever pulled
        between computes, never during one."""
        probe = _OverlapProbe(n_chunks=6)
        with Prefetcher(probe.source(), depth=1) as pf:
            for _ in pf:
                probe.compute()
        assert probe.overlaps == 0

    def test_backpressure_bounds_lead(self):
        """A fast producer over a slow consumer must stall at ``depth``
        chunks ahead — the semaphore is acquired BEFORE the source pull,
        so even the pull count is bounded."""
        for depth in (2, 3):
            probe = _OverlapProbe(n_chunks=12, pull_sleep=0.0)
            with Prefetcher(probe.source(), depth=depth) as pf:
                for _ in pf:
                    probe.compute(seconds=0.02)
            assert probe.pulled == probe.n_chunks
            assert probe.max_lead <= depth + 1, (
                f"producer ran {probe.max_lead} chunks ahead at depth "
                f"{depth}"
            )


class TestErrorsAndShutdown:
    def test_source_error_propagates_with_type_and_message(self):
        def gen():
            yield np.zeros((4, 2))
            raise OSError("disk vanished mid-pass")

        src = ChunkSource(gen, n_features=2, chunk_rows=4)
        for depth in (1, 2):
            got = []
            with pytest.raises(OSError, match="disk vanished"):
                with Prefetcher(src, depth=depth) as pf:
                    for chunk, n_valid in pf:
                        got.append(n_valid)
            assert got == [4]

    def test_stage_error_propagates(self):
        def bad_stage(item):
            if item == 3:
                raise RuntimeError("stage blew up on item 3")
            return item

        with pytest.raises(RuntimeError, match="item 3"):
            with Prefetcher(range(10), stage=bad_stage, depth=2) as pf:
                list(pf)

    def test_error_reaches_pass_guard(self):
        """End to end through the real consumer: a mid-pass source error
        must surface out of streamed_accumulate via _PassGuard (the
        multi-process fail-fast path), prefetch or not."""
        from oap_mllib_tpu.ops import stream_ops

        def gen():
            yield np.zeros((8, 3))
            raise ValueError("rank-local staging failure")

        for depth in (1, 2):
            set_config(prefetch_depth=depth)
            src = ChunkSource(gen, n_features=3, chunk_rows=8)
            with pytest.raises(ValueError, match="staging failure"):
                stream_ops.streamed_accumulate(
                    src, np.zeros((2, 3), np.float32), np.float32,
                    "highest", need_cost=False,
                )

    def test_early_exit_shuts_producer_down(self):
        """Breaking out mid-pass (or a consumer exception) must cancel
        the producer thread, even while it is blocked on backpressure."""
        probe = _OverlapProbe(n_chunks=50, pull_sleep=0.0)
        pf = Prefetcher(probe.source(), depth=2)
        it = iter(pf)
        next(it)
        pf.close()
        thread = pf._impl._thread
        thread.join(timeout=5.0)
        assert not thread.is_alive()
        assert probe.pulled < probe.n_chunks  # it did NOT drain the source

    def test_context_manager_exit_on_consumer_exception(self):
        probe = _OverlapProbe(n_chunks=50, pull_sleep=0.0)
        with pytest.raises(KeyError):
            with Prefetcher(probe.source(), depth=3) as pf:
                for _ in pf:
                    raise KeyError("consumer bug")
        thread = pf._impl._thread
        thread.join(timeout=5.0)
        assert not thread.is_alive()

    def test_exhaustion_joins_thread(self):
        with Prefetcher(range(4), depth=2) as pf:
            assert list(pf) == [0, 1, 2, 3]
        assert not pf._impl._thread.is_alive()

    def test_no_leaked_threads_on_clean_paths(self):
        """``PrefetchStats.leaked_threads`` counts producer threads that
        failed to join at shutdown — it must be zero on every clean
        path: exhaustion, early close, and consumer exception."""
        stats = PrefetchStats()
        with Prefetcher(range(8), depth=2, stats=stats) as pf:
            list(pf)
        assert stats.leaked_threads == 0

        stats = PrefetchStats()
        pf = Prefetcher(range(50), depth=3, stats=stats)
        next(iter(pf))
        pf.close()
        assert stats.leaked_threads == 0

        stats = PrefetchStats()
        with pytest.raises(KeyError):
            with Prefetcher(range(50), depth=2, stats=stats) as pf:
                for _ in pf:
                    raise KeyError("consumer bug")
        assert stats.leaked_threads == 0

    def test_wedged_producer_counts_as_leaked(self):
        """A stage callable that never returns must be COUNTED (and the
        daemon thread abandoned), not silently ignored — the satellite
        contract.  The wedged thread holds no queue slot the consumer
        needs, so close() returns promptly with leaked_threads == 1."""
        release = threading.Event()

        def wedge(item):
            if item == 1:
                release.wait(timeout=30.0)  # far past the 5 s join budget
            return item

        stats = PrefetchStats()
        pf = Prefetcher(range(4), stage=wedge, depth=2, stats=stats)
        it = iter(pf)
        assert next(it) == 0  # item 1 is now staging (wedged) in producer
        t0 = time.perf_counter()
        pf.close()
        release.set()  # let the thread die after the verdict
        assert stats.leaked_threads == 1
        assert time.perf_counter() - t0 < 20.0  # close() did not hang

    def test_wedged_producer_is_poisoned_after_close(self, monkeypatch):
        """ISSUE 14 satellite: close() on a wedged producer must not
        just count the leak — it marks the source exhausted and swaps
        the staging queue for a poison queue, so when the wedged thread
        finally wakes it (a) cannot put its staged chunk anywhere a
        consumer could see and (b) ends at its next source pull instead
        of staging into a retired pipeline forever."""
        from oap_mllib_tpu.data import prefetch as pf_mod

        monkeypatch.setattr(pf_mod, "JOIN_TIMEOUT_S", 0.2)
        release = threading.Event()
        pulled = []

        def source():
            for i in range(8):
                pulled.append(i)
                yield i

        def wedge(item):
            if item == 1:
                release.wait(timeout=30.0)  # deliberately blocked stage
            return item

        stats = PrefetchStats()
        pf = Prefetcher(source(), stage=wedge, depth=2, stats=stats)
        it = iter(pf)
        assert next(it) == 0  # item 1 is now wedged inside the producer
        impl = pf._impl
        real_q = impl._q
        pf.close()
        assert stats.leaked_threads == 1
        # the pipeline is quarantined: poison queue in place, source off
        assert isinstance(impl._q, pf_mod._PoisonQueue)
        assert impl._items._closed
        producer = impl._thread
        release.set()  # the wedged stage finally returns...
        producer.join(timeout=5.0)
        # ...and the thread EXITS: its put was discarded by the poison
        # queue and its next source pull hit the closed source
        assert not producer.is_alive()
        assert real_q.empty(), "a late stage wrote into the retired queue"
        assert len(pulled) <= 3, "a wedged producer kept draining the source"

    def test_poison_queue_retires_late_jax_arrays(self):
        """A late put's device buffers are retired on arrival (the
        'cannot write into a retired buffer' half of the contract)."""
        import jax.numpy as jnp

        from oap_mllib_tpu.data import prefetch as pf_mod

        arr = jnp.ones((4, 4))
        pf_mod._PoisonQueue(True).put((arr, 1))
        assert arr.is_deleted()

    def test_streamed_fit_leaks_no_threads(self, rng):
        """The estimator surface: a streamed fit's summary reports zero
        leaked prefetch threads (counter wired end to end)."""
        from oap_mllib_tpu import KMeans

        x = rng.normal(size=(400, 5)).astype(np.float32)
        src = ChunkSource.from_array(x, chunk_rows=128)
        m = KMeans(k=3, max_iter=3, seed=0).fit(src)
        assert m.summary.accelerated
        import threading as _threading

        leftover = [
            t for t in _threading.enumerate()
            if t.name.startswith("oap-mllib-tpu-prefetch") and t.is_alive()
        ]
        assert leftover == []


@pytest.mark.slow
class TestWallClock:
    """Wall-clock speedup checks — inherently timing-sensitive, so they
    carry the ``slow`` marker and stay OUT of the deterministic tier-1
    ``-m 'not slow'`` gate (dev/ci.sh runs them in the full suite)."""

    def test_depth2_beats_serial_on_balanced_load(self):
        def run(depth):
            probe = _OverlapProbe(n_chunks=12, pull_sleep=0.03)
            t0 = time.perf_counter()
            with Prefetcher(probe.source(), depth=depth) as pf:
                for _ in pf:
                    probe.compute(seconds=0.03)
            return time.perf_counter() - t0

        t_serial = run(1)
        t_pipe = run(2)
        # balanced 30ms/30ms stages: perfect overlap would halve the
        # wall; demand a conservative 25% to stay robust on loaded CI
        assert t_pipe < t_serial * 0.75, (t_serial, t_pipe)


class TestStatsAndTimings:
    def test_stats_account_chunks_and_stage_time(self):
        stats = PrefetchStats()

        def stage(v):
            with stats.transfer():
                time.sleep(0.001)
            return v

        with Prefetcher(range(8), stage=stage, depth=2, stats=stats) as pf:
            list(pf)
        assert stats.chunks == 8
        assert stats.transfer_s > 0
        assert stats.stage_s >= stats.transfer_s

    def test_finalize_writes_split_and_overlap_efficiency(self):
        from oap_mllib_tpu.utils.timing import Timings

        t = Timings()
        stats = PrefetchStats()
        stats.stage_s, stats.transfer_s, stats.wait_s = 0.5, 0.2, 0.1
        stats.finalize(t, "lloyd_loop", wall=1.0)
        d = t.as_dict()
        assert d["lloyd_loop/stage"] == pytest.approx(0.3)
        assert d["lloyd_loop/transfer"] == pytest.approx(0.2)
        assert d["lloyd_loop/compute"] == pytest.approx(0.9)
        assert t.subphases("lloyd_loop")["stream_wall"] == pytest.approx(1.0)
        # wait 0.1 of 0.5 staging -> 80% hidden
        assert t.overlap_efficiency("lloyd_loop") == pytest.approx(0.8)
        assert t.overlap_efficiency("not_streamed") is None

    def test_streamed_fit_records_split(self, rng):
        """The estimator surface: a streamed K-Means summary carries the
        stage/transfer/compute split for both fit phases."""
        from oap_mllib_tpu import KMeans

        x = rng.normal(size=(600, 5)).astype(np.float32)
        src = ChunkSource.from_array(x, chunk_rows=128)
        m = KMeans(k=3, max_iter=5, seed=0).fit(src)
        ph = m.summary.timings.as_dict()
        for phase in ("lloyd_loop", "init_centers"):
            for sub in ("stage", "transfer", "compute", "stream_wall"):
                assert f"{phase}/{sub}" in ph, (phase, sub, sorted(ph))
        assert m.summary.timings.overlap_efficiency("lloyd_loop") is not None
