"""oaplint unit tests: per-rule fixture snippets (positive + negative +
suppression), the suppression grammar, and the meta-test that the SHIPPED
tree lints clean.

The positive fixtures double as the mutation check: each one is a seeded
violation of exactly the invariant its rule encodes, linted under a
pretend in-scope path through the ``lint_text`` seam — if a refactor
weakens a rule, its seeded violation stops being caught and the
parametrized test fails by name.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "dev"))

import oaplint  # noqa: E402


def lint(rel, text, rules=None, kind="py"):
    return oaplint.lint_text(rel, text, rules=rules, kind=kind)


def rules_of(findings):
    return sorted({f.rule for f in findings})


OPS = "oap_mllib_tpu/ops/fake.py"
MODELS = "oap_mllib_tpu/models/fake.py"
STREAM = "oap_mllib_tpu/ops/fake_stream.py"


# ---------------------------------------------------------------------------
# seeded violations: one per rule (the mutation check)
# ---------------------------------------------------------------------------

SEEDED = {
    "jit-outside-progcache": (MODELS, "import jax\nf = jax.jit(g)(x)\n"),
    "raw-matmul": (OPS, "import jax.numpy as jnp\ny = jnp.dot(a, b)\n"),
    "raw-collective": (OPS, "from jax import lax\ns = lax.psum(x, 'i')\n"),
    "stream-host-sync": (
        STREAM, "import jax\njax.block_until_ready(x)\n"),
    "traced-python-branch": (
        OPS,
        "import jax\n\n\n@jax.jit\ndef f(x):\n"
        "    if x > 0:\n        return x\n    return -x\n",
    ),
    "unregistered-fault-site": (
        OPS,
        "from oap_mllib_tpu.utils.faults import maybe_fault\n"
        "maybe_fault('no.such.site')\n",
    ),
    "nondeterminism": (
        OPS, "import time\nt0 = time.time()\nprint(t0)\n"),
    "fit-missing-finalize": (
        MODELS,
        "def fit(self, x):\n    out = resilient_fit(run, cfg)\n"
        "    return out\n",
    ),
    "trailing-whitespace": (OPS, "x = 1 \n"),
    "tab": (OPS, "if True:\n\tx = 1\n"),
    "line-length": (OPS, "x = '" + "a" * 120 + "'\n"),
    "final-newline": (OPS, "x = 1"),
    "unused-import": (OPS, "import os\nx = 1\n"),
    # ISSUE 7 dataflow rules (dev/oaplint/dataflow.py): one seeded
    # violating module per rule, analyzed against the LIVE package index
    "collective-divergence": (
        OPS,
        "import jax\n"
        "from oap_mllib_tpu.parallel import collective\n\n\n"
        "def f(x, mesh):\n"
        "    r = jax.process_index()\n"
        "    if r == 0:\n"
        "        x = collective.allreduce_sum(x, mesh)\n"
        "    return x\n",
    ),
    "unbound-collective-axis": (
        OPS,
        "from oap_mllib_tpu.parallel import collective\n\n\n"
        "def f(x):\n"
        "    return collective.psum(x, 'rows')\n",
    ),
    "precision-flow": (
        OPS,
        "import jax.numpy as jnp\n\n\n"
        "def f(x):\n"
        "    y = x.astype(jnp.bfloat16)\n"
        "    return jnp.sum(y)\n",
    ),
    # ISSUE 14 concurrency rules (dev/oaplint/concurrency.py): one
    # seeded violating module per rule, analyzed against the live
    # package's thread/lock model
    "lock-order-inversion": (
        OPS,
        "import threading\n\n"
        "_A = threading.Lock()\n_B = threading.Lock()\n\n\n"
        "def f():\n    with _A:\n        with _B:\n            pass\n\n\n"
        "def g():\n    with _B:\n        with _A:\n            pass\n",
    ),
    "unguarded-shared-write": (
        OPS,
        "import threading\n\n_STATE = {}\n\n\n"
        "def _worker():\n    _STATE['n'] = 1\n\n\n"
        "def start():\n"
        "    t = threading.Thread(target=_worker, daemon=True)\n"
        "    t.start()\n"
        "    _STATE['n'] = 2\n",
    ),
    "blocking-while-locked": (
        OPS,
        "import threading\nimport time\n\n_lock = threading.Lock()\n\n\n"
        "def f():\n    with _lock:\n        time.sleep(0.1)\n",
    ),
    "unjoined-thread": (
        OPS,
        "import threading\n\n\n"
        "def f(work):\n"
        "    t = threading.Thread(target=work)\n"
        "    t.start()\n",
    ),
    "atexit-outside-shutdown": (
        OPS,
        "import atexit\n\n\n"
        "def f():\n    atexit.register(f)\n",
    ),
}


@pytest.mark.parametrize("rule", sorted(SEEDED))
def test_seeded_violation_is_caught(rule):
    rel, text = SEEDED[rule]
    found = lint(rel, text, rules=[rule])
    assert rules_of(found) == [rule], (
        f"seeded {rule} violation was not caught: {found}")


def test_findings_carry_position_and_render_contract():
    rel, text = SEEDED["raw-matmul"]
    (f,) = lint(rel, text, rules=["raw-matmul"])
    assert (f.path, f.line) == (rel, 2)
    assert f.render().startswith(f"{rel}:2: raw-matmul: ")
    assert json.loads(oaplint.to_json([f]))[0]["rule"] == "raw-matmul"


# ---------------------------------------------------------------------------
# R1: jit routing
# ---------------------------------------------------------------------------


def test_jit_inside_get_or_build_lambda_is_allowed():
    text = (
        "import jax\nfrom oap_mllib_tpu.utils import progcache\n"
        "fn = progcache.get_or_build('a', ('k',), lambda: jax.jit(g))\n"
    )
    assert lint(MODELS, text, rules=["jit-outside-progcache"]) == []


def test_jit_inside_named_builder_fn_is_allowed():
    text = (
        "import jax\nfrom oap_mllib_tpu.utils import progcache\n\n\n"
        "def _build():\n    return jax.jit(g)\n\n\n"
        "fn = progcache.get_or_build('a', ('k',), _build)\n"
    )
    assert lint(OPS, text, rules=["jit-outside-progcache"]) == []


def test_jit_decorator_allowed_in_ops_only():
    text = "import jax\n\n\n@jax.jit\ndef f(x):\n    return x\n"
    assert lint(OPS, text, rules=["jit-outside-progcache"]) == []
    assert rules_of(lint(MODELS, text, rules=["jit-outside-progcache"])) \
        == ["jit-outside-progcache"]


def test_progcache_module_itself_is_exempt():
    text = "import jax\nf = jax.jit(g)\n"
    assert lint("oap_mllib_tpu/utils/progcache.py", text,
                rules=["jit-outside-progcache"]) == []


# ---------------------------------------------------------------------------
# R2: precision-policy matmuls
# ---------------------------------------------------------------------------


def test_pdot_and_host_numpy_matmuls_are_clean():
    text = (
        "import numpy as np\nfrom oap_mllib_tpu.utils import "
        "precision as psn\ny = psn.pdot(a, b)\nz = np.dot(c, d)\n"
    )
    assert lint(OPS, text, rules=["raw-matmul"]) == []


def test_at_matmul_and_einsum_flagged_pallas_exempt():
    text = "import jax.numpy as jnp\ny = a @ b\nz = jnp.einsum('ij,jk', a, b)\n"
    found = lint(MODELS, text, rules=["raw-matmul"])
    assert [f.line for f in found] == [2, 3]
    assert lint("oap_mllib_tpu/ops/pallas/fake.py", text,
                rules=["raw-matmul"]) == []


def test_matmul_outside_ops_models_is_out_of_scope():
    text = "import jax.numpy as jnp\ny = jnp.dot(a, b)\n"
    assert lint("oap_mllib_tpu/utils/fake.py", text,
                rules=["raw-matmul"]) == []


# ---------------------------------------------------------------------------
# R3: collective facade
# ---------------------------------------------------------------------------


def test_collective_facade_and_own_module_are_clean():
    text = (
        "from oap_mllib_tpu.parallel import collective\n"
        "s = collective.psum(x, 'i')\n"
    )
    assert lint(OPS, text, rules=["raw-collective"]) == []
    raw = "from jax import lax\ns = lax.psum(x, 'i')\n"
    assert lint("oap_mllib_tpu/parallel/collective.py", raw,
                rules=["raw-collective"]) == []


PALLAS = "oap_mllib_tpu/ops/pallas/fake_kernel.py"

_REMOTE_DMA = (
    "from jax.experimental.pallas import tpu as pltpu\n\n\n"
    "def _kernel(src, dst, send_sem, recv_sem):\n"
    "    rdma = pltpu.make_async_remote_copy(\n"
    "        src_ref=src, dst_ref=dst, send_sem=send_sem,\n"
    "        recv_sem=recv_sem, device_id=(1,),\n"
    "    )\n"
    "    rdma.start()\n"
    "    rdma.wait()\n"
    "    pltpu.semaphore_signal(send_sem, inc=1, device_id=(1,))\n"
    "    pltpu.semaphore_wait(recv_sem, 1)\n"
)


def test_remote_dma_exempt_inside_pallas_flagged_outside():
    """ISSUE 9 R3 extension: pltpu remote-DMA/semaphore primitives are
    the kernel plane's collectives — exempt inside ops/pallas/, findings
    anywhere else (an ad-hoc remote DMA in ops/ would bypass every
    accounting seam)."""
    assert lint(PALLAS, _REMOTE_DMA, rules=["raw-collective"]) == []
    found = lint(OPS, _REMOTE_DMA, rules=["raw-collective"])
    assert rules_of(found) == ["raw-collective"]
    assert len(found) == 3  # remote copy + signal + wait all fire


def test_raw_psum_inside_pallas_kernel_body_still_fires():
    """Seeded mutation: the ops/pallas/ exemption is primitive-scoped —
    a raw lax.psum snuck into a kernel body must still be a finding
    (the ring kernel's host-level reductions go through the facade)."""
    text = (
        "from jax import lax\n\n\n"
        "def _kernel(x_ref, o_ref):\n"
        "    o_ref[...] = lax.psum(x_ref[...], 'data')\n"
    )
    assert rules_of(lint(PALLAS, text, rules=["raw-collective"])) == [
        "raw-collective"
    ]


# ---------------------------------------------------------------------------
# R4: streamed-loop host sync
# ---------------------------------------------------------------------------

_LOOP_TMPL = (
    "import jax\nimport numpy as np\n"
    "from oap_mllib_tpu.data.prefetch import Prefetcher\n\n\n"
    "def run(items):\n"
    "    pf = Prefetcher(items)\n"
    "    for chunk in pf:\n"
    "        {body}\n"
)


@pytest.mark.parametrize("body", [
    "jax.device_get(chunk)",
    "chunk.item()",
    "h = np.asarray(chunk)",
    "v = float(compute(chunk))",
])
def test_host_sync_in_prefetch_loop_flagged(body):
    found = lint(STREAM, _LOOP_TMPL.format(body=body),
                 rules=["stream-host-sync"])
    assert rules_of(found) == ["stream-host-sync"]


def test_host_fetch_outside_loop_or_of_host_values_is_clean():
    text = _LOOP_TMPL.format(body="total = accumulate(chunk)") + (
        "    h = np.asarray(total)\n"
    )
    assert lint(STREAM, text, rules=["stream-host-sync"]) == []
    # np.asarray of a non-chunk name inside the loop: no sync on a
    # device value, clean
    text2 = _LOOP_TMPL.format(body="h = np.asarray(host_side)")
    assert lint(STREAM, text2, rules=["stream-host-sync"]) == []


def test_barrier_needs_reasoned_suppression():
    text = (
        "import jax\n"
        "# oaplint: disable=stream-host-sync -- end-of-fit barrier\n"
        "jax.block_until_ready(x)\n"
    )
    assert lint(STREAM, text, rules=["stream-host-sync"]) == []


# ---------------------------------------------------------------------------
# R5: traced control flow
# ---------------------------------------------------------------------------


def test_static_args_metadata_and_is_none_are_exempt():
    text = (
        "from functools import partial\n\nimport jax\n\n\n"
        "@partial(jax.jit, static_argnames=('n',))\n"
        "def f(x, mask, n):\n"
        "    if n > 2:\n        pass\n"
        "    if x.shape[0] > 1:\n        pass\n"
        "    if mask is None:\n        pass\n"
        "    return x\n"
    )
    assert lint(OPS, text, rules=["traced-python-branch"]) == []


def test_while_and_len_on_traced_values_flagged():
    text = (
        "import jax\n\n\n@jax.jit\ndef f(x):\n"
        "    while x > 0:\n        x = x - 1\n"
        "    n = len(x)\n"
        "    return x + n\n"
    )
    found = lint(OPS, text, rules=["traced-python-branch"])
    assert len(found) == 2


def test_undecorated_function_is_out_of_scope():
    text = "def f(x):\n    if x > 0:\n        return x\n    return -x\n"
    assert lint(OPS, text, rules=["traced-python-branch"]) == []


# ---------------------------------------------------------------------------
# R7: fault-site registry
# ---------------------------------------------------------------------------


def test_registered_site_is_clean():
    text = (
        "from oap_mllib_tpu.utils.faults import maybe_fault\n"
        "maybe_fault('stream.read')\n"
    )
    assert lint(OPS, text, rules=["unregistered-fault-site"]) == []


# ---------------------------------------------------------------------------
# R8: determinism
# ---------------------------------------------------------------------------


def test_seeded_rng_and_tick_are_clean():
    text = (
        "import numpy as np\nfrom oap_mllib_tpu.utils.timing import tick\n"
        "rng = np.random.default_rng(7)\nelapsed = tick()\n"
    )
    assert lint("oap_mllib_tpu/data/fake.py", text,
                rules=["nondeterminism"]) == []


def test_unseeded_rng_legacy_np_random_and_import_random_flagged():
    text = (
        "import random\nimport numpy as np\n"
        "r1 = np.random.default_rng()\nr2 = np.random.rand(3)\n"
    )
    found = lint(OPS, text, rules=["nondeterminism"])
    assert len(found) == 3


def test_wall_clock_outside_compute_plane_is_out_of_scope():
    text = "import time\nt0 = time.time()\nprint(t0)\n"
    assert lint("oap_mllib_tpu/telemetry/fake.py", text,
                rules=["nondeterminism"]) == []


# ---------------------------------------------------------------------------
# R9: telemetry finalize
# ---------------------------------------------------------------------------


def test_fit_with_finalize_is_clean():
    text = (
        "def fit(self, x):\n    out = resilient_fit(run, cfg)\n"
        "    return finalize_fit('als', out)\n"
    )
    assert lint(MODELS, text, rules=["fit-missing-finalize"]) == []


# ---------------------------------------------------------------------------
# R10 style details
# ---------------------------------------------------------------------------


def test_noqa_and_init_reexports_opt_out_of_unused_import():
    assert lint(OPS, "import os  # noqa: F401\nx = 1\n",
                rules=["unused-import"]) == []
    assert lint("oap_mllib_tpu/fake/__init__.py", "import os\n",
                rules=["unused-import"]) == []


def test_syntax_error_is_a_finding_not_a_crash():
    found = lint(OPS, "def f(:\n")
    assert rules_of(found) == ["syntax"]


# ---------------------------------------------------------------------------
# suppression grammar
# ---------------------------------------------------------------------------


def test_inline_suppression_with_reason():
    text = ("import jax.numpy as jnp\n"
            "y = jnp.dot(a, b)  "
            "# oaplint: disable=raw-matmul -- parity probe\n")
    assert lint(OPS, text, rules=["raw-matmul"]) == []


def test_suppression_without_reason_is_rejected_and_does_not_apply():
    text = ("import jax.numpy as jnp\n"
            "y = jnp.dot(a, b)  # oaplint: disable=raw-matmul\n")
    found = lint(OPS, text, rules=["raw-matmul"])
    assert rules_of(found) == ["bad-suppression", "raw-matmul"]


def test_suppression_of_unknown_rule_is_rejected():
    # built by concatenation so the live-tree lint of THIS file does not
    # parse the fixture as a real (and invalid) directive
    text = "x = 1  # oaplint" ": disable=no-such-rule -- whatever\n"
    found = lint(OPS, text, rules=["final-newline"])
    assert rules_of(found) == ["bad-suppression"]


def test_comment_line_suppression_applies_to_next_line_only():
    text = (
        "import jax.numpy as jnp\n"
        "# oaplint: disable=raw-matmul -- audited\n"
        "y = jnp.dot(a, b)\n"
        "z = jnp.dot(a, b)\n"
    )
    found = lint(OPS, text, rules=["raw-matmul"])
    assert [f.line for f in found] == [4]


def test_multi_rule_suppression_comma_list():
    text = (
        "import jax.numpy as jnp\nfrom jax import lax\n"
        "# oaplint: disable=raw-matmul, raw-collective -- audited pair\n"
        "y = lax.psum(jnp.dot(a, b), 'i')\n"
    )
    assert lint(OPS, text, rules=["raw-matmul", "raw-collective"]) == []


# ---------------------------------------------------------------------------
# R16-R18: the interprocedural dataflow rules (dev/oaplint/dataflow.py)
# ---------------------------------------------------------------------------


def test_r16_interprocedural_reach_and_provenance_chain():
    """A call that only TRANSITIVELY reaches a collective, under a
    branch whose condition flows from process_index through a local,
    is flagged — and the finding prints both chains."""
    text = (
        "import jax\n"
        "from oap_mllib_tpu.ops import stream_ops\n\n\n"
        "def f(arrays):\n"
        "    me = jax.process_index()\n"
        "    lead = me == 0\n"
        "    if lead:\n"
        "        return stream_ops._psum_host(arrays)\n"
        "    return arrays\n"
    )
    (f,) = lint(OPS, text, rules=["collective-divergence"])
    assert "_psum_host" in f.detail
    # the reach chain ends at a collective — since ISSUE 9 the shortest
    # path runs through the ring plane (ring_allreduce) rather than
    # process_allgather, either endpoint proves transitive reach
    assert "ring_allreduce" in f.detail or "process_allgather" in f.detail
    assert "process_index" in f.detail  # the provenance chain
    assert f.line == 9


def test_r16_uniformized_condition_is_clean():
    """A gather re-uniformizes: branching on a process_allgather'd
    maximum is world-consistent, so a collective under it is fine (the
    _gathered_triple_chunks shape in ops/als_block_stream.py)."""
    text = (
        "import numpy as np\n"
        "from jax.experimental import multihost_utils\n"
        "from oap_mllib_tpu.ops import stream_ops\n\n\n"
        "def f(arrays, n_local):\n"
        "    n_max = int(np.asarray(multihost_utils.process_allgather(\n"
        "        np.asarray([n_local]))).max())\n"
        "    if n_max > 0:\n"
        "        return stream_ops._psum_host(arrays)\n"
        "    return arrays\n"
    )
    assert lint(OPS, text, rules=["collective-divergence"]) == []


def test_r16_rank_divergent_loop_flagged():
    """Per-rank trip counts diverge too: a collective inside a loop
    over rank-derived data is the same hang with more steps."""
    text = (
        "import jax\n"
        "from oap_mllib_tpu.parallel import collective\n\n\n"
        "def f(x, mesh, blocks):\n"
        "    mine = [b for b in blocks if b % jax.process_count()\n"
        "            == jax.process_index()]\n"
        "    for b in mine:\n"
        "        x = collective.allreduce_sum(x, mesh)\n"
        "    return x\n"
    )
    found = lint(OPS, text, rules=["collective-divergence"])
    assert [f.line for f in found] == [9]


def test_r17_axis_resolved_through_helper_to_config_is_clean():
    text = (
        "from oap_mllib_tpu.config import get_config\n"
        "from oap_mllib_tpu.parallel import collective\n\n\n"
        "def helper(x, axis):\n"
        "    return collective.psum(x, axis)\n\n\n"
        "def entry(x):\n"
        "    cfg = get_config()\n"
        "    return helper(x, cfg.data_axis)\n"
    )
    assert lint(OPS, text, rules=["unbound-collective-axis"]) == []


def test_r17_literal_bound_by_local_shard_map_spec_is_clean():
    text = (
        "from jax.sharding import PartitionSpec as P\n"
        "from oap_mllib_tpu.parallel import collective\n"
        "from oap_mllib_tpu.utils.jax_compat import shard_map\n\n\n"
        "def f(x, mesh):\n"
        "    def inner(blk):\n"
        "        return collective.psum(blk, 'data')\n\n"
        "    return shard_map(inner, mesh=mesh, in_specs=(P('data'),),\n"
        "                     out_specs=P())(x)\n"
    )
    assert lint(OPS, text, rules=["unbound-collective-axis"]) == []


def test_r17_names_the_unbound_literal_and_its_origin():
    text = (
        "from oap_mllib_tpu.parallel import collective\n\n\n"
        "def helper(x, axis):\n"
        "    return collective.psum(x, axis)\n\n\n"
        "def entry(x):\n"
        "    return helper(x, 'rows')\n"
    )
    (f,) = lint(OPS, text, rules=["unbound-collective-axis"])
    assert "'rows'" in f.detail and f.line == 5


BAL = "oap_mllib_tpu/parallel/fake_balance.py"


def test_r16_balance_scope_rank_gated_capability_sync():
    """ISSUE 15: the capability allgather must be rank-uniform — a
    planner-shaped module gating ops/stream_ops.capability_sync (which
    transitively reaches the host allgather) on process_index is
    exactly the hang R16 exists to catch, and parallel/ is in scope."""
    text = (
        "import jax\n"
        "from oap_mllib_tpu.ops import stream_ops\n\n\n"
        "def world_capabilities(frame):\n"
        "    if jax.process_index() == 0:\n"
        "        return stream_ops.capability_sync(frame)\n"
        "    return None\n"
    )
    found = lint(BAL, text, rules=["collective-divergence"])
    assert [f.line for f in found] == [7]
    assert "capability_sync" in found[0].detail
    assert "process_index" in found[0].detail


def test_r16_balance_rank_derived_extent_loop_flagged():
    """A planner iterating rank-derived extents around a collective
    diverges trip counts — same hang, more steps."""
    text = (
        "import jax\n"
        "from oap_mllib_tpu.ops import stream_ops\n\n\n"
        "def replan(arrays, extents):\n"
        "    mine = extents[jax.process_index()]\n"
        "    for _ in range(mine):\n"
        "        arrays = stream_ops._psum_host(arrays)\n"
        "    return arrays\n"
    )
    found = lint(BAL, text, rules=["collective-divergence"])
    assert [f.line for f in found] == [8]


def test_r16_balance_gathered_decision_is_clean():
    """The live controller's shape: branching on GATHERED (therefore
    rank-identical) frames before a collective is world-uniform."""
    text = (
        "import numpy as np\n"
        "from oap_mllib_tpu.ops import stream_ops\n\n\n"
        "def observe(frame, arrays):\n"
        "    gathered = stream_ops.capability_sync(frame)\n"
        "    if float(np.asarray(gathered).max()) > 1.5:\n"
        "        return stream_ops._psum_host(arrays)\n"
        "    return arrays\n"
    )
    assert lint(BAL, text, rules=["collective-divergence"]) == []


def test_r17_balance_scope_unbound_axis():
    """R17 covers parallel/balance-shaped modules: a collective whose
    axis resolves to no mesh binding is flagged there too."""
    text = (
        "from oap_mllib_tpu.parallel import collective\n\n\n"
        "def fold(x):\n"
        "    return collective.psum(x, 'balance_axis')\n"
    )
    (f,) = lint(BAL, text, rules=["unbound-collective-axis"])
    assert "'balance_axis'" in f.detail and f.line == 5


def test_r18_upcast_and_matmul_consumers_are_clean():
    text = (
        "import jax.numpy as jnp\n"
        "from oap_mllib_tpu.utils import precision as psn\n\n\n"
        "def f(x, c):\n"
        "    y = x.astype(jnp.bfloat16)\n"
        "    g = psn.pdot(y, c, 'bf16')\n"
        "    s = jnp.sum(psn.upcast(y))\n"
        "    return g, s\n"
    )
    assert lint(OPS, text, rules=["precision-flow"]) == []


def test_r18_roundtrip_and_bf16_accumulator_flagged():
    text = (
        "import jax.numpy as jnp\n\n\n"
        "def f(x):\n"
        "    acc = jnp.zeros((4,), dtype=jnp.bfloat16)\n"
        "    z = x.astype(jnp.bfloat16).astype(jnp.float32)\n"
        "    return acc, z\n"
    )
    found = lint(OPS, text, rules=["precision-flow"])
    assert [f.line for f in found] == [5, 6]


def test_r18_pallas_kernels_are_exempt():
    text = (
        "import jax.numpy as jnp\n\n\n"
        "def split(a):\n"
        "    hi = a.astype(jnp.bfloat16)\n"
        "    lo = (a - hi.astype(jnp.float32)).astype(jnp.bfloat16)\n"
        "    return hi, lo\n"
    )
    assert lint("oap_mllib_tpu/ops/pallas/fake.py", text,
                rules=["precision-flow"]) == []


# ---------------------------------------------------------------------------
# R19-R22: the concurrency pass (dev/oaplint/concurrency.py, ISSUE 14)
# ---------------------------------------------------------------------------


def test_r19_interprocedural_inversion_prints_both_chains():
    """An inversion where one leg acquires through a HELPER is still a
    cycle, and the finding names both acquisition chains."""
    text = (
        "import threading\n\n"
        "_A = threading.Lock()\n_B = threading.Lock()\n\n\n"
        "def helper():\n    with _B:\n        pass\n\n\n"
        "def f():\n    with _A:\n        helper()\n\n\n"
        "def g():\n    with _B:\n        with _A:\n            pass\n"
    )
    found = lint(OPS, text, rules=["lock-order-inversion"])
    assert rules_of(found) == ["lock-order-inversion"]
    assert any("helper" in f.detail and "_A" in f.detail
               and "_B" in f.detail for f in found)


def test_r19_consistent_global_order_is_clean():
    text = (
        "import threading\n\n"
        "_A = threading.Lock()\n_B = threading.Lock()\n\n\n"
        "def f():\n    with _A:\n        with _B:\n            pass\n\n\n"
        "def g():\n    with _A:\n        with _B:\n            pass\n"
    )
    assert lint(OPS, text, rules=["lock-order-inversion"]) == []


def test_r19_reentrant_same_lock_is_not_a_cycle():
    text = (
        "import threading\n\n_R = threading.RLock()\n\n\n"
        "def f():\n    with _R:\n        with _R:\n            pass\n"
    )
    assert lint(OPS, text, rules=["lock-order-inversion"]) == []


def test_r20_lock_guarded_writes_are_clean():
    text = (
        "import threading\n\n_STATE = {}\n_lock = threading.Lock()\n\n\n"
        "def _worker():\n    with _lock:\n        _STATE['n'] = 1\n\n\n"
        "def start():\n"
        "    t = threading.Thread(target=_worker, daemon=True)\n"
        "    t.start()\n"
        "    with _lock:\n        _STATE['n'] = 2\n"
    )
    assert lint(OPS, text, rules=["unguarded-shared-write"]) == []


def test_r20_helper_called_under_lock_inherits_the_guard():
    """The _shutdown_locked convention: a helper only ever called with
    the lock held writes under that lock for R20's purposes (the
    always-held intersection over call sites)."""
    text = (
        "import threading\n\n_STATE = {}\n_lock = threading.Lock()\n\n\n"
        "def _locked_write():\n    _STATE['n'] = 1\n\n\n"
        "def _worker():\n    with _lock:\n        _locked_write()\n\n\n"
        "def start():\n"
        "    t = threading.Thread(target=_worker, daemon=True)\n"
        "    t.start()\n"
        "    with _lock:\n        _locked_write()\n"
    )
    assert lint(OPS, text, rules=["unguarded-shared-write"]) == []


def test_r20_main_only_global_is_out_of_scope():
    """A global never touched by any spawned-thread closure is not
    shared state — single-threaded mutation needs no lock."""
    text = (
        "_CACHE = {}\n\n\n"
        "def remember(k, v):\n    _CACHE[k] = v\n"
    )
    assert lint(OPS, text, rules=["unguarded-shared-write"]) == []


def test_r20_finding_names_roots_and_write_sites():
    rel, text = SEEDED["unguarded-shared-write"]
    (f,) = lint(rel, text, rules=["unguarded-shared-write"])
    assert "_STATE" in f.detail and "_worker" in f.detail
    assert "thread target" in f.detail and "holding no lock" in f.detail


def test_r21_interprocedural_block_chain():
    """Blocking reached through a call chain under a lock is flagged at
    the call site, printing the chain to the blocking op."""
    text = (
        "import threading\nimport time\n\n_lock = threading.Lock()\n\n\n"
        "def slow():\n    time.sleep(0.1)\n\n\n"
        "def f():\n    with _lock:\n        slow()\n"
    )
    found = lint(OPS, text, rules=["blocking-while-locked"])
    assert rules_of(found) == ["blocking-while-locked"]
    assert any("slow" in f.detail and "time.sleep" in f.detail
               for f in found)


def test_r21_blocking_outside_the_critical_section_is_clean():
    text = (
        "import threading\nimport time\n\n_lock = threading.Lock()\n\n\n"
        "def f():\n    with _lock:\n        x = 1\n    time.sleep(0.1)\n"
    )
    assert lint(OPS, text, rules=["blocking-while-locked"]) == []


def test_r21_str_join_is_not_a_thread_join():
    text = (
        "import threading\n\n_lock = threading.Lock()\n\n\n"
        "def f(parts):\n    with _lock:\n"
        "        return ', '.join(parts)\n"
    )
    assert lint(OPS, text, rules=["blocking-while-locked"]) == []


def test_r21_collective_under_lock_is_the_starvation_shape():
    text = (
        "import threading\n\n"
        "from oap_mllib_tpu.parallel import collective\n\n"
        "_lock = threading.Lock()\n\n\n"
        "def f(x, mesh):\n"
        "    with _lock:\n"
        "        return collective.allreduce_sum(x, mesh)\n"
    )
    found = lint(OPS, text, rules=["blocking-while-locked"])
    assert rules_of(found) == ["blocking-while-locked"]


def test_r22_daemon_and_joined_threads_are_clean():
    daemon = (
        "import threading\n\n\n"
        "def f(work):\n"
        "    t = threading.Thread(target=work, daemon=True)\n"
        "    t.start()\n"
    )
    joined = (
        "import threading\n\n\n"
        "def f(work):\n"
        "    t = threading.Thread(target=work)\n"
        "    t.start()\n"
        "    t.join()\n"
    )
    later_daemon = (
        "import threading\n\n\n"
        "def f(work):\n"
        "    t = threading.Thread(target=work)\n"
        "    t.daemon = True\n"
        "    t.start()\n"
    )
    for text in (daemon, joined, later_daemon):
        assert lint(OPS, text, rules=["unjoined-thread"]) == []


def test_r22_self_attribute_handle_joined_elsewhere_is_clean():
    """The prefetch shape: the handle lands on self in __init__ and a
    different method joins it."""
    text = (
        "import threading\n\n\n"
        "class P:\n"
        "    def __init__(self, work):\n"
        "        self._thread = threading.Thread(target=work)\n"
        "        self._thread.start()\n\n"
        "    def close(self):\n"
        "        self._thread.join(timeout=5.0)\n"
    )
    assert lint(OPS, text, rules=["unjoined-thread"]) == []


def test_atexit_register_allowed_only_in_export():
    text = "import atexit\n\n\ndef g():\n    pass\n\n\natexit.register(g)\n"
    assert lint("oap_mllib_tpu/telemetry/export.py", text,
                rules=["atexit-outside-shutdown"]) == []
    found = lint("oap_mllib_tpu/telemetry/fleet.py", text,
                 rules=["atexit-outside-shutdown"])
    assert rules_of(found) == ["atexit-outside-shutdown"]


def test_concurrency_suppression_applies():
    text = (
        "import threading\nimport time\n\n_lock = threading.Lock()\n\n\n"
        "def f():\n"
        "    with _lock:\n"
        "        # oaplint: disable=blocking-while-locked -- audited\n"
        "        time.sleep(0.1)\n"
    )
    assert lint(OPS, text, rules=["blocking-while-locked"]) == []


# ---------------------------------------------------------------------------
# unused-suppression detection + the inventory (ISSUE 7 satellite)
# ---------------------------------------------------------------------------


def test_unused_suppression_is_flagged():
    text = (
        "import numpy as np\n"
        "# oaplint: disable=raw-matmul -- stale: the dot moved away\n"
        "y = np.copy(a)\n"
    )
    found = lint(OPS, text)  # all rules: unused detection active
    assert "unused-suppression" in rules_of(found)
    (f,) = [f for f in found if f.rule == "unused-suppression"]
    assert f.line == 2 and "'raw-matmul'" in f.detail


def test_used_suppression_is_not_flagged():
    text = (
        "import jax.numpy as jnp\n"
        "y = jnp.dot(a, b)  # oaplint: disable=raw-matmul -- parity probe\n"
    )
    assert [f for f in lint(OPS, text)
            if f.rule == "unused-suppression"] == []


def test_subset_rule_runs_skip_unused_detection():
    """With only some rules active a directive cannot be proven dead."""
    text = (
        "import numpy as np\n"
        "# oaplint: disable=raw-matmul -- audited\n"
        "y = np.copy(a)\n"
    )
    assert lint(OPS, text, rules=["raw-matmul"]) == []


def test_directive_inside_string_literal_is_not_a_directive():
    """Suppression syntax quoted in a docstring or fixture string must
    neither suppress nor count as an (unused) directive — directives
    are parsed from real comment tokens only."""
    text = (
        'DOC = """example:\n'
        "    # oaplint: disable=raw-matmul -- why\n"
        '"""\n'
        "import jax.numpy as jnp\n"
        "y = jnp.dot(a, b)\n"
    )
    found = lint(OPS, text, rules=["raw-matmul"])
    assert rules_of(found) == ["raw-matmul"]  # the string did not suppress
    assert [f for f in lint(OPS, text)
            if f.rule == "unused-suppression"] == []


def test_suppression_inventory_shape_and_usage():
    findings, _ = oaplint.run(ROOT)
    inv = oaplint.suppression_inventory(ROOT, findings)
    assert inv, "the live tree carries audited suppressions"
    for rec in inv:
        assert set(rec) == {"path", "line", "target", "rules", "reason",
                            "used"}
        assert rec["reason"], f"reasonless directive in inventory: {rec}"
        assert rec["used"] is True, f"stale directive shipped: {rec}"


# ---------------------------------------------------------------------------
# R6: the project-wide Config contract (fixture tree)
# ---------------------------------------------------------------------------

_CONFIG_SRC = (
    "import dataclasses\n\n\n"
    "@dataclasses.dataclass\nclass Config:\n    alpha: float = 1.0\n"
)


def _project_tree(tmp_path, doc="`alpha`", cover=True, extra_env=None):
    pkg = tmp_path / "oap_mllib_tpu"
    pkg.mkdir()
    (pkg / "config.py").write_text(_CONFIG_SRC)
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "configuration.md").write_text(f"| {doc} | doc |\n")
    (tmp_path / "tests").mkdir()
    (tmp_path / "tests" / "test_config_coverage.py").write_text(
        "import dataclasses\nfor f in dataclasses.fields(Config):\n"
        "    pass\n" if cover else "x = 1\n"
    )
    if extra_env:
        (pkg / "io.py").write_text(f"VAR = {extra_env!r}\n")
    return tmp_path


def _project_findings(root):
    findings, _ = oaplint.run(root, rules=["config-field-contract"],
                              paths=[])
    return findings


def test_config_contract_clean_tree(tmp_path):
    assert _project_findings(_project_tree(tmp_path)) == []


def test_config_contract_flags_undocumented_field(tmp_path):
    found = _project_findings(_project_tree(tmp_path, doc="`other`"))
    assert len(found) == 1 and "not documented" in found[0].detail


def test_config_contract_flags_uncovered_field(tmp_path):
    found = _project_findings(_project_tree(tmp_path, cover=False))
    assert len(found) == 1 and "not covered" in found[0].detail


def test_config_contract_flags_mismatched_env_literal(tmp_path):
    found = _project_findings(
        _project_tree(tmp_path, extra_env="OAP_MLLIB_TPU_BOGUS"))
    assert len(found) == 1 and "OAP_MLLIB_TPU_BOGUS" in found[0].detail


def test_config_contract_matching_env_literal_is_clean(tmp_path):
    assert _project_findings(
        _project_tree(tmp_path, extra_env="OAP_MLLIB_TPU_ALPHA")) == []


# ---------------------------------------------------------------------------
# the gate: the live tree lints clean, with enough rules active
# ---------------------------------------------------------------------------
# ISSUE 13: the serving plane is covered by the invariant rules — one
# seeded violation per rule, linted under a serving/ path, proving R1
# (jit-only-via-progcache), R2 (precision-routed matmuls; scope grew
# from ops|models to ops|models|serving), and R3 (facade-only
# collectives) all fire inside the new package.
# ---------------------------------------------------------------------------

SERVING = "oap_mllib_tpu/serving/fake.py"

_SERVING_SEEDED = [
    ("jit-outside-progcache", "import jax\nf = jax.jit(score)(x)\n"),
    ("raw-matmul", "import jax.numpy as jnp\ns = jnp.dot(q, t.T)\n"),
    ("raw-matmul", "s = q @ t.T\n"),
    ("raw-collective", "from jax import lax\ny = lax.ppermute(x, 'data', p)\n"),
    ("raw-collective", "from jax import lax\ny = lax.psum(x, 'data')\n"),
]


@pytest.mark.parametrize(
    "rule,text", _SERVING_SEEDED,
    ids=[f"{r}-{i}" for i, (r, _) in enumerate(_SERVING_SEEDED)],
)
def test_serving_scope_seeded_violation_is_caught(rule, text):
    found = lint(SERVING, text, rules=[rule])
    assert rules_of(found) == [rule], (
        f"seeded serving-scope {rule} violation was not caught: {found}")


def test_serving_pdot_and_facade_are_clean():
    text = (
        "from oap_mllib_tpu.parallel import collective\n"
        "from oap_mllib_tpu.utils import precision as psn\n\n\n"
        "def score(q, t, axis):\n"
        "    s = psn.pdot(q, t.T, 'f32', 'highest')\n"
        "    return collective.ppermute(s, axis, [(0, 1)])\n"
    )
    assert lint(SERVING, text,
                rules=["raw-matmul", "raw-collective"]) == []


def test_serving_jit_inside_builder_is_allowed():
    text = (
        "import jax\nfrom oap_mllib_tpu.utils import progcache\n\n\n"
        "def _build(tier):\n"
        "    return jax.jit(lambda x: x)\n\n\n"
        "fn = progcache.get_or_build('serve.x', ('k',), lambda: _build('hi'))\n"
    )
    assert lint(SERVING, text, rules=["jit-outside-progcache"]) == []


def test_live_tree_lints_clean():
    findings, n_files = oaplint.run(ROOT)
    assert findings == [], "\n".join(f.render() for f in findings)
    assert n_files > 80  # the whole tree was actually enumerated


def test_rule_count_floor():
    # ISSUE 6 acceptance: >= 9 active contract/style rules
    assert len(oaplint.RULES) >= 9


def test_every_suppression_in_tree_carries_reason():
    # the runner rejects reasonless directives as findings; this asserts
    # the stronger property directly on the shipped tree's directives
    import re

    pat = re.compile(r"oaplint:\s*disable=")
    ok = re.compile(r"oaplint:\s*disable=[\w\-, ]+?--\s*\S")
    for path, kind in oaplint.iter_files(ROOT):
        for i, line in enumerate(path.read_text().splitlines(), 1):
            if "test_oaplint" in path.name:
                continue  # fixture strings exercise the bad grammar
            if pat.search(line):
                assert ok.search(line), f"{path}:{i}: reasonless directive"
