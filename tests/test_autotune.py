"""ISSUE 17 autotuner + double-buffered-walk tests.

The tuner's determinism contract is CACHE-mediated, not timing-mediated:
a sweep's winner persists under ``Config.tuning_cache_dir`` and every
later resolution (same process or a fresh one) reads it back — so the
tests assert cache behavior and geometry identity, never wall clocks.
The kernel-geometry legs pin the load-bearing invariant instead: every
(tile_rows, depth, batch) choice routes through the same per-tile math,
so geometry may move overlap but never a result bit (K-Means/ALS exact,
PCA within 1e-6 for the XLA-walk tile order).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.ops.pallas import autotune


@pytest.fixture(autouse=True)
def _clean_tuning():
    autotune.clear()
    set_config(tuning="auto", tuning_cache_dir="")
    yield
    autotune.clear()
    set_config(tuning="auto", tuning_cache_dir="")


# ---------------------------------------------------------------------------
# mode parsing / validation
# ---------------------------------------------------------------------------


class TestParseMode:
    def test_plain_modes(self):
        for m in autotune.MODES:
            assert autotune.parse_mode(m) == (m, None)

    def test_typo_raises(self):
        with pytest.raises(ValueError, match="tuning"):
            autotune.parse_mode("onn")

    def test_pin_parses(self):
        mode, pins = autotune.parse_mode(
            'pin:{"kmeans": {"tile_rows": 1024}}'
        )
        assert mode == "pin"
        assert pins == {"kmeans": {"tile_rows": 1024}}

    def test_pin_bad_json_raises(self):
        with pytest.raises(ValueError, match="JSON"):
            autotune.parse_mode("pin:{nope")

    def test_pin_unknown_kernel_raises(self):
        with pytest.raises(ValueError, match="kmean"):
            autotune.parse_mode('pin:{"kmean": {"tile_rows": 512}}')

    def test_pin_unknown_knob_raises(self):
        with pytest.raises(ValueError, match="tile_row"):
            autotune.parse_mode('pin:{"kmeans": {"tile_row": 512}}')

    def test_pin_non_integer_raises(self):
        with pytest.raises(ValueError, match="integer"):
            autotune.parse_mode('pin:{"kmeans": {"tile_rows": "big"}}')

    def test_typo_raises_at_fit_entry(self, rng):
        """The repo's dispatch-knob contract: a Config.tuning typo must
        raise at fit entry (utils/dispatch.should_accelerate), never
        silently tune nothing."""
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(tuning="onn")
        x = rng.normal(size=(64, 4)).astype(np.float32)
        with pytest.raises(ValueError, match="tuning"):
            KMeans(k=2, init_mode="random", max_iter=1).fit(x)


# ---------------------------------------------------------------------------
# shape buckets
# ---------------------------------------------------------------------------


class TestShapeBucket:
    def test_rounds_up_to_pow2(self):
        assert autotune.shape_bucket(3) == (4,)
        assert autotune.shape_bucket(129, 256) == (256, 256)
        assert autotune.shape_bucket(1) == (1,)

    def test_nearby_shapes_share_a_bucket(self):
        assert autotune.shape_bucket(100, 33) == autotune.shape_bucket(
            65, 64
        )


# ---------------------------------------------------------------------------
# the resolve ladder
# ---------------------------------------------------------------------------


def _sweep_count(kernel):
    from oap_mllib_tpu.telemetry import metrics as tm

    return tm.counter("oap_tuning_sweeps_total", {"kernel": kernel}).value


class TestResolveLadder:
    def test_unknown_kernel_raises(self):
        with pytest.raises(ValueError, match="kernel"):
            autotune.resolve("kmean", (64, 64))

    def test_auto_never_sweeps(self):
        before = _sweep_count("kmeans")
        geo = autotune.resolve("kmeans", (64, 64))
        assert geo == autotune.DEFAULTS["kmeans"]
        assert _sweep_count("kmeans") == before
        d = autotune.delta(autotune.mark() - 1)
        assert d["decisions"][-1]["decision"] == "default"

    def test_off_ignores_cache(self, tmp_path):
        set_config(tuning="on", tuning_cache_dir=str(tmp_path))
        tuned = autotune.resolve("kmeans", (64, 64), interpret=True)
        assert autotune._valid_geometry("kmeans", tuned)
        set_config(tuning="off")
        geo = autotune.resolve("kmeans", (64, 64), interpret=True)
        assert geo == autotune.DEFAULTS["kmeans"]

    def test_pin_overlays_defaults_verbatim(self):
        set_config(tuning='pin:{"kmeans": {"tile_rows": 1024}}')
        geo = autotune.resolve("kmeans", (64, 64))
        assert geo == {"tile_rows": 1024,
                       "depth": autotune.DEFAULTS["kmeans"]["depth"]}
        # a pinned kernel never consults cache or sweeps; unpinned
        # kernels fall through the normal ladder
        assert autotune.resolve("pca", (64,)) == autotune.DEFAULTS["pca"]

    def test_on_sweeps_once_then_hits(self, tmp_path):
        set_config(tuning="on", tuning_cache_dir=str(tmp_path))
        before = _sweep_count("kmeans")
        g1 = autotune.resolve("kmeans", (64, 64), interpret=True)
        assert _sweep_count("kmeans") == before + 1
        g2 = autotune.resolve("kmeans", (64, 64), interpret=True)
        assert g2 == g1
        assert _sweep_count("kmeans") == before + 1  # hit, no re-sweep
        mark = autotune.mark()
        autotune.resolve("kmeans", (64, 64), interpret=True)
        assert autotune.delta(mark)["hits"] == 1

    def test_disk_round_trip_across_clear(self, tmp_path):
        """The cross-process determinism contract, in-process: the
        persisted winner survives a full in-memory wipe (what a fresh
        interpreter sees) and resolves with ZERO additional sweeps."""
        set_config(tuning="on", tuning_cache_dir=str(tmp_path))
        g1 = autotune.resolve("kmeans", (64, 64), interpret=True)
        files = os.listdir(tmp_path)
        assert len(files) == 1 and files[0].startswith("tune-")
        with open(tmp_path / files[0]) as f:
            entry = json.load(f)
        assert entry["kernel"] == "kmeans"
        assert {k: int(v) for k, v in entry["geometry"].items()} == g1

        autotune.clear()  # fresh-process stand-in
        before = _sweep_count("kmeans")
        g2 = autotune.resolve("kmeans", (64, 64), interpret=True)
        assert g2 == g1
        assert _sweep_count("kmeans") == before  # disk hit, zero sweeps

    def test_corrupt_cache_warns_and_resweeps(self, tmp_path, caplog):
        set_config(tuning="on", tuning_cache_dir=str(tmp_path))
        g1 = autotune.resolve("kmeans", (64, 64), interpret=True)
        (path,) = [tmp_path / f for f in os.listdir(tmp_path)]
        path.write_text("{ not json")
        autotune.clear()
        before = _sweep_count("kmeans")
        with caplog.at_level("WARNING", logger="oap_mllib_tpu"):
            g2 = autotune.resolve("kmeans", (64, 64), interpret=True)
        assert any("unreadable" in r.message for r in caplog.records)
        assert _sweep_count("kmeans") == before + 1  # fresh sweep
        # determinism is cache-mediated, not timing-mediated: the fresh
        # sweep re-persists a valid winner (which one depends on walls)
        assert autotune._valid_geometry("kmeans", g2)
        assert g1 is not g2
        assert json.loads(path.read_text())["geometry"] == g2

    def test_stale_key_reads_as_miss(self, tmp_path, caplog):
        """An entry whose recorded key does not match (e.g. a cache dir
        shared across backends) is ignored with a warning, never
        misapplied."""
        set_config(tuning="on", tuning_cache_dir=str(tmp_path))
        autotune.resolve("kmeans", (64, 64), interpret=True)
        (path,) = [tmp_path / f for f in os.listdir(tmp_path)]
        entry = json.loads(path.read_text())
        entry["key"] = "('other-backend',)"
        path.write_text(json.dumps(entry))
        autotune.clear()
        before = _sweep_count("kmeans")
        with caplog.at_level("WARNING", logger="oap_mllib_tpu"):
            autotune.resolve("kmeans", (64, 64), interpret=True)
        assert _sweep_count("kmeans") == before + 1

    def test_tier_is_part_of_the_key(self, tmp_path):
        set_config(tuning="on", tuning_cache_dir=str(tmp_path))
        autotune.resolve("kmeans", (64, 64), "highest", interpret=True)
        before = _sweep_count("kmeans")
        autotune.resolve("kmeans", (64, 64), "default", interpret=True)
        assert _sweep_count("kmeans") == before + 1  # distinct key


# ---------------------------------------------------------------------------
# fit-summary integration
# ---------------------------------------------------------------------------


class TestSummaryTuning:
    def test_kmeans_summary_records_tuning(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        x = rng.normal(size=(256, 8)).astype(np.float32)
        m = KMeans(k=3, init_mode="random", max_iter=2).fit(x)
        t = m.summary.tuning
        assert t["mode"] == "auto"
        assert t["sweeps"] == 0  # auto NEVER sweeps
        assert any(d["kernel"] == "kmeans" for d in t["decisions"])

    def test_pca_and_als_summaries_record_tuning(self, rng):
        from oap_mllib_tpu.models.als import ALS
        from oap_mllib_tpu.models.pca import PCA

        x = rng.normal(size=(128, 6)).astype(np.float32)
        assert PCA(k=2).fit(x).summary["tuning"]["mode"] == "auto"
        u = rng.integers(0, 30, 300)
        i = rng.integers(0, 20, 300)
        r = (rng.random(300) * 4 + 1).astype(np.float32)
        m = ALS(rank=3, max_iter=1).fit(u, i, r)
        assert m.summary["tuning"]["mode"] == "auto"

    def test_second_fit_same_bucket_zero_sweeps(self, rng, tmp_path):
        """Mode "on": the first fit sweeps, the second fit on the same
        (backend, bucket) resolves entirely from cache."""
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(tuning="on", tuning_cache_dir=str(tmp_path))
        x = rng.normal(size=(256, 8)).astype(np.float32)
        m1 = KMeans(k=3, init_mode="random", max_iter=2).fit(x)
        m2 = KMeans(k=3, init_mode="random", max_iter=2).fit(x)
        assert m2.summary.tuning["sweeps"] == 0
        assert m1.summary.tuning["sweeps"] >= m2.summary.tuning["sweeps"]


# ---------------------------------------------------------------------------
# cross-process determinism (the acceptance leg; slow — subprocess + jax)
# ---------------------------------------------------------------------------


_CHILD = r"""
import json, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.ops.pallas import autotune
from oap_mllib_tpu.telemetry import metrics as tm

set_config(tuning="on", tuning_cache_dir=sys.argv[1])
geo = autotune.resolve("kmeans", (64, 64), interpret=True)
print(json.dumps({
    "geometry": geo,
    "sweeps": tm.counter(
        "oap_tuning_sweeps_total", {"kernel": "kmeans"}
    ).value,
}))
"""


@pytest.mark.slow
class TestCrossProcessDeterminism:
    def test_fresh_process_reuses_the_persisted_winner(self, tmp_path):
        """Two FRESH interpreters sharing one tuning_cache_dir: the
        first sweeps once, the second resolves the identical geometry
        with zero sweeps — rank-uniformity (R16) and restart-stability
        both hang off this."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = []
        for _ in range(2):
            p = subprocess.run(
                [sys.executable, "-c", _CHILD, str(tmp_path)],
                capture_output=True, text=True, env=env,
                cwd=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                timeout=300,
            )
            assert p.returncode == 0, p.stderr[-2000:]
            out.append(json.loads(p.stdout.strip().splitlines()[-1]))
        assert out[0]["sweeps"] == 1.0
        assert out[1]["sweeps"] == 0.0  # cache-mediated, no re-sweep
        assert out[1]["geometry"] == out[0]["geometry"]


# ---------------------------------------------------------------------------
# geometry moves overlap, never bits
# ---------------------------------------------------------------------------


GEOMETRIES = [(256, 2), (512, 2), (512, 3), (1024, 3)]


class TestGeometryParity:
    def test_kmeans_walk_bit_identical_across_depth_and_route(self, rng):
        """At a FIXED tile partition, buffering depth and dispatch route
        (interpret DMA walk vs the schedule-identical XLA scan) change
        overlap only — the f32 sums must be bit-identical.  Across
        different tile_rows the chunk reduction reorders, so that axis
        gets a scaled 1e-6 bound instead."""
        from oap_mllib_tpu.ops.pallas.kmeans_kernel import (
            lloyd_accumulate_walk,
        )

        x = jnp.asarray(rng.normal(size=(700, 9)).astype(np.float32))
        w = jnp.ones((700,), jnp.float32)
        c = jnp.asarray(rng.normal(size=(5, 9)).astype(np.float32))
        refs = {}
        for tile_rows, depth in GEOMETRIES:
            for interp in (True, False):
                out = lloyd_accumulate_walk(
                    x, w, c, interpret=interp, tile_rows=tile_rows,
                    depth=depth,
                )
                out = tuple(np.asarray(o) for o in out)
                if tile_rows not in refs:
                    refs[tile_rows] = out
                for a, b in zip(out, refs[tile_rows]):
                    assert np.array_equal(a, b), (tile_rows, depth, interp)
        # across tile partitions: same values up to f32 reassociation
        vals = list(refs.values())
        for other in vals[1:]:
            for a, b in zip(other, vals[0]):
                scale = max(1.0, float(np.abs(b).max()))
                np.testing.assert_allclose(a, b, atol=1e-6 * scale)

    def test_kmeans_walk_matches_grid_kernel_at_its_partition(self, rng):
        """The dbuf walk at the grid kernel's own tile partition
        (_BLOCK_ROWS) shares _tile_update with it — bit-identical."""
        from oap_mllib_tpu.ops.pallas.kmeans_kernel import (
            _BLOCK_ROWS,
            lloyd_accumulate_pallas,
            lloyd_accumulate_walk,
        )

        x = jnp.asarray(rng.normal(size=(700, 9)).astype(np.float32))
        w = jnp.ones((700,), jnp.float32)
        c = jnp.asarray(rng.normal(size=(5, 9)).astype(np.float32))
        ref = lloyd_accumulate_pallas(x, w, c, interpret=True)
        out = lloyd_accumulate_walk(
            x, w, c, interpret=True, tile_rows=_BLOCK_ROWS, depth=2
        )
        for a, b in zip(out, ref):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_pca_moments_within_1e6_across_geometry(self, rng):
        from oap_mllib_tpu.ops.pallas.pca_kernel import pca_moments_pallas

        x = jnp.asarray(rng.normal(size=(900, 17)).astype(np.float32))
        m = jnp.ones((900,), jnp.float32)
        g_ref, cs_ref, n_ref = pca_moments_pallas(x, m, interpret=True)
        scale = max(1.0, float(np.abs(np.asarray(g_ref)).max()))
        for tile_rows, depth in GEOMETRIES:
            for interp in (True, False):
                g, cs, n = pca_moments_pallas(
                    x, m, interpret=interp, tile_rows=tile_rows,
                    depth=depth,
                )
                np.testing.assert_allclose(
                    np.asarray(g), np.asarray(g_ref), atol=1e-6 * scale,
                    err_msg=f"geometry {(tile_rows, depth, interp)}",
                )
                np.testing.assert_allclose(
                    np.asarray(cs), np.asarray(cs_ref), atol=1e-6 * scale,
                )
                assert float(n) == float(n_ref)

    def test_als_solve_bit_identical_across_batch(self, rng):
        """The batched solve is row-independent — batch geometry cannot
        move a bit."""
        from oap_mllib_tpu.ops.pallas.als_kernel import (
            solve_normal_eq_pallas,
        )

        n, r = 300, 8
        mm = rng.normal(size=(n, r, r)).astype(np.float32)
        a = jnp.asarray(
            np.einsum("nij,nkj->nik", mm, mm) + 0.5 * np.eye(r)
        )
        b = jnp.asarray(rng.normal(size=(n, r)).astype(np.float32))
        n_reg = jnp.asarray(np.ones((n,), np.float32) * 3)
        ref = solve_normal_eq_pallas(a, b, n_reg, 0.1, interpret=True)
        for batch, depth in ((128, 2), (256, 3), (512, 2)):
            out = solve_normal_eq_pallas(
                a, b, n_reg, 0.1, interpret=True, batch=batch, depth=depth
            )
            assert np.array_equal(np.asarray(out), np.asarray(ref)), (
                batch, depth,
            )

    def test_als_gram_bit_identical_across_geometry(self, rng):
        from oap_mllib_tpu.ops.pallas.als_kernel import factor_gram_pallas

        f = jnp.asarray(rng.normal(size=(777, 10)).astype(np.float32))
        refs = {}
        for tile_rows, depth in GEOMETRIES:
            out = np.asarray(factor_gram_pallas(
                f, interpret=True, tile_rows=tile_rows, depth=depth
            ))
            # depth never moves a bit at a fixed partition
            if tile_rows in refs:
                assert np.array_equal(out, refs[tile_rows]), (
                    tile_rows, depth,
                )
            refs[tile_rows] = out
        vals = list(refs.values())
        scale = max(1.0, float(np.abs(vals[0]).max()))
        for other in vals[1:]:
            np.testing.assert_allclose(other, vals[0], atol=1e-6 * scale)

    def test_tuned_kmeans_fit_matches_untuned(self, rng, tmp_path):
        """End to end: a pinned non-default geometry fit must agree with
        the default-geometry fit (1e-6 — the XLA route re-chunks the
        Lloyd scan, which reorders the f32 chunk reduction)."""
        from oap_mllib_tpu.models.kmeans import KMeans

        x = rng.normal(size=(512, 8)).astype(np.float32)
        kw = dict(k=3, init_mode="random", max_iter=3, seed=7)
        m1 = KMeans(**kw).fit(x)
        set_config(tuning='pin:{"kmeans": {"tile_rows": 256, "depth": 3}}')
        m2 = KMeans(**kw).fit(x)
        np.testing.assert_allclose(
            m1.cluster_centers_, m2.cluster_centers_, atol=1e-6, rtol=1e-6
        )
        assert m2.summary.tuning["decisions"][-1]["decision"] == "pin"
