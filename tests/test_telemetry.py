"""Telemetry subsystem tests (ISSUE 4): span tree + Timings views,
metrics registry, counter absorption from the existing subsystems,
exporters, and the telemetry-off no-op contract."""

import json

import numpy as np
import pytest

from oap_mllib_tpu import telemetry
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.telemetry import metrics as tm
from oap_mllib_tpu.telemetry.spans import Span, current_span, enter
from oap_mllib_tpu.utils.timing import Timings, phase_timer


class TestSpans:
    def test_nesting_and_paths(self):
        root = Span("fit")
        root.node("a/b").record(1.0)
        root.node("a").record(2.0)
        root.node("a/b").record(0.5)
        a = root.child("a")
        assert [c.name for c in root.children] == ["a"]
        assert [c.name for c in a.children] == ["b"]
        assert a.duration_s == pytest.approx(2.0)
        assert a.child("b").duration_s == pytest.approx(1.5)
        assert a.child("b").count == 2

    def test_flat_excludes_unrecorded_containers(self):
        """Implicit path containers (count=0) must not appear in the
        flat view — the old record list only held explicit adds."""
        root = Span("fit")
        root.node("phase/compile").record(0.25)
        assert root.flat() == {"phase/compile": pytest.approx(0.25)}

    def test_walk_and_as_dict(self):
        root = Span("fit")
        root.node("x/y").record(1.0)
        paths = [p for p, _ in root.walk()]
        assert paths == ["fit", "fit/x", "fit/x/y"]
        d = root.as_dict()
        assert d["name"] == "fit"
        assert d["children"][0]["children"][0]["name"] == "y"

    def test_attributes_and_collective_notes(self):
        sp = Span("phase")
        sp.note_collective("allreduce_sum", 1024, 0.01)
        sp.note_collective("allreduce_sum", 1024, 0.02)
        sp.note_collective("broadcast", 64, 0.001)
        coll = sp.attrs["collectives"]
        assert coll["allreduce_sum"]["ops"] == 2
        assert coll["allreduce_sum"]["bytes"] == 2048
        assert coll["broadcast"]["ops"] == 1

    def test_enter_stack_and_timing(self):
        sp = Span("outer")
        inner = sp.child("inner")
        assert current_span() is None
        with enter(sp):
            assert current_span() is sp
            with enter(inner):
                assert current_span() is inner
            assert current_span() is sp
        assert current_span() is None
        assert sp.count == 1 and sp.duration_s > 0
        assert inner.duration_s <= sp.duration_s

    def test_enter_records_on_exception(self):
        sp = Span("s")
        with pytest.raises(RuntimeError):
            with enter(sp):
                raise RuntimeError("boom")
        assert sp.count == 1
        assert current_span() is None


class TestTimingsViews:
    """Timings accessors must return exactly what the flat record list
    returned (the backward-compat contract of the storage swap)."""

    def test_add_and_as_dict_sum_duplicates(self):
        t = Timings()
        t.add("a", 1.0)
        t.add("b/c", 0.5)
        t.add("a", 0.25)
        assert t.as_dict() == {
            "a": pytest.approx(1.25), "b/c": pytest.approx(0.5)
        }
        assert t.total() == pytest.approx(1.75)

    def test_subphases(self):
        t = Timings()
        t.add("lloyd_loop", 2.0)
        t.add("lloyd_loop/stage", 0.3)
        t.add("lloyd_loop/compute", 1.6)
        assert t.subphases("lloyd_loop") == {
            "stage": pytest.approx(0.3), "compute": pytest.approx(1.6)
        }

    def test_overlap_efficiency_matches_pre_span_formula(self):
        t = Timings()
        t.add("p/stage", 0.3)
        t.add("p/transfer", 0.2)
        t.add("p/compute", 0.9)
        t.add("p/stream_wall", 1.0)
        # wait = 1.0 - 0.9 = 0.1 of 0.5 staging -> 80% hidden
        assert t.overlap_efficiency("p") == pytest.approx(0.8)
        assert t.overlap_efficiency("absent") is None

    def test_compile_split(self):
        t = Timings()
        assert t.compile_split("p") is None
        t.add("p/compile", 0.7)
        assert t.compile_split("p") == {
            "compile": pytest.approx(0.7), "execute": 0.0
        }

    def test_phase_timer_records_into_tree(self):
        t = Timings("kmeans.fit")
        with phase_timer(t, "lloyd_loop"):
            pass
        assert t.root.name == "kmeans.fit"
        assert "lloyd_loop" in t.as_dict()
        assert t.root.child("lloyd_loop").count == 1

    def test_phase_log_names_owner_and_rank(self, caplog):
        """The ISSUE 4 satellite: concurrent fits' phase lines must be
        attributable — the root name (and the rank, multi-process) ride
        the log line."""
        import logging

        set_config(timing=True)
        t = Timings("pca.fit")
        with caplog.at_level(logging.INFO, logger="oap_mllib_tpu"):
            t.add("covariance", 0.5)
        assert "pca.fit" in caplog.text and "covariance" in caplog.text
        set_config(num_processes=4, process_id=2)
        with caplog.at_level(logging.INFO, logger="oap_mllib_tpu"):
            t.add("eigh", 0.1)
        assert "pca.fit[r2]" in caplog.text


class TestMetricsRegistry:
    def setup_method(self):
        tm.reset()

    def test_counter_and_gauge(self):
        tm.counter("t_total").inc()
        tm.counter("t_total").inc(2.5)
        tm.gauge("t_gauge").set(7)
        snap = tm.snapshot()
        assert snap["t_total"][""] == pytest.approx(3.5)
        assert snap["t_gauge"][""] == 7

    def test_labels_are_distinct_series(self):
        tm.counter("ops", {"op": "a"}).inc()
        tm.counter("ops", {"op": "b"}).inc(3)
        snap = tm.snapshot()
        assert snap["ops"] == {"op=a": 1, "op=b": 3}

    def test_histogram_bucket_edges(self):
        """Fixed log-scale bounds: a value equal to a bound lands IN
        that bound's bucket (le semantics); past the last bound lands
        in +Inf."""
        h = tm.histogram("h", bounds=(1.0, 4.0, 16.0))
        for v in (0.5, 1.0, 1.0001, 4.0, 16.0, 17.0):
            h.observe(v)
        assert h.counts == [2, 2, 1, 1]  # [<=1, <=4, <=16, +Inf]
        assert h.count == 6
        assert h.sum == pytest.approx(0.5 + 1.0 + 1.0001 + 4.0 + 16.0 + 17.0)

    def test_default_buckets_are_log_scale(self):
        bs = tm.DURATION_BUCKETS
        assert all(
            bs[i + 1] / bs[i] == pytest.approx(4.0)
            for i in range(len(bs) - 1)
        )

    def test_type_conflict_raises(self):
        tm.counter("conflicted")
        with pytest.raises(ValueError, match="already registered"):
            tm.gauge("conflicted")

    def test_prometheus_rendering(self):
        tm.counter("c_total", {"algo": "kmeans"}, help="a counter").inc(2)
        h = tm.histogram("lat_seconds", bounds=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        text = tm.render_prometheus()
        assert "# HELP c_total a counter" in text
        assert "# TYPE c_total counter" in text
        assert 'c_total{algo="kmeans"} 2' in text
        assert "# TYPE lat_seconds histogram" in text
        # cumulative buckets: 1 at <=0.1, still 1 at <=1.0, 2 at +Inf
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="1"} 1' in text
        assert 'lat_seconds_bucket{le="+Inf"} 2' in text
        assert "lat_seconds_count 2" in text


class TestPrometheusRoundTrip:
    """ISSUE 11 satellite: the text exposition must hold the promtext
    spec — verified by PARSING it back and cross-checking against the
    registry, not by substring spot checks."""

    def setup_method(self):
        tm.reset()

    @staticmethod
    def _parse(text):
        """Minimal promtext parser: {family: {"type", "help",
        "samples": {(suffix, labels-str): value}}}.  Raises on any line
        that fits neither comment nor sample grammar."""
        import re

        fams = {}
        sample_re = re.compile(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (\S+)$"
        )
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith("# HELP "):
                _, _, name, help_ = line.split(" ", 3)
                fams.setdefault(name, {"samples": {}})["help"] = help_
            elif line.startswith("# TYPE "):
                _, _, name, kind = line.split(" ", 3)
                fams.setdefault(name, {"samples": {}})["type"] = kind
            else:
                m = sample_re.match(line)
                assert m, f"unparsable exposition line: {line!r}"
                name, labels, value = m.groups()
                base = name
                for suffix in ("_bucket", "_sum", "_count"):
                    if name.endswith(suffix) and name[: -len(suffix)] in fams:
                        base = name[: -len(suffix)]
                        break
                fams.setdefault(base, {"samples": {}})["samples"][
                    (name, labels or "")
                ] = float(value.replace("+Inf", "inf"))
        return fams

    def test_every_family_has_help_and_type(self):
        tm.counter("rt_total", help="with help").inc()
        tm.counter("rt_helpless_total").inc()  # registered help-less
        fams = self._parse(tm.render_prometheus())
        for name, fam in fams.items():
            assert "type" in fam, f"{name} missing # TYPE"
            assert "help" in fam, f"{name} missing # HELP"
        assert fams["rt_total"]["help"] == "with help"
        # help-less registration gets the self-naming fallback
        assert fams["rt_helpless_total"]["help"]

    def test_help_upgraded_when_richer_site_registers(self):
        tm.counter("rt_lazy_total").inc()
        tm.counter("rt_lazy_total", help="the real help").inc()
        fams = self._parse(tm.render_prometheus())
        assert fams["rt_lazy_total"]["help"] == "the real help"

    def test_histogram_cumulative_inf_count_sum_consistent(self):
        h = tm.histogram("rt_seconds", bounds=(0.1, 1.0, 10.0),
                         help="hist")
        values = [0.05, 0.1, 0.5, 2.0, 50.0, 50.0]
        for v in values:
            h.observe(v)
        fams = self._parse(tm.render_prometheus())
        samples = fams["rt_seconds"]["samples"]
        buckets = {
            labels: v for (name, labels), v in samples.items()
            if name == "rt_seconds_bucket"
        }
        # cumulative and non-decreasing in le order, +Inf == _count
        ordered = [buckets[f'{{le="{le}"}}']
                   for le in ("0.1", "1", "10", "+Inf")]
        assert ordered == sorted(ordered)
        assert ordered[0] == 2  # 0.05 and the le-inclusive 0.1
        assert ordered[-1] == len(values)
        assert samples[("rt_seconds_count", "")] == len(values)
        assert samples[("rt_seconds_sum", "")] == pytest.approx(
            sum(values)
        )

    def test_label_values_escaped(self):
        tm.counter(
            "rt_esc_total",
            {"path": 'a"b\\c', "msg": "two\nlines"},
            help="escapes",
        ).inc()
        text = tm.render_prometheus()
        assert '\\"' in text and "\\\\" in text and "\\n" in text
        fams = self._parse(text)  # the escaped line still parses
        assert any(
            name == "rt_esc_total"
            for (name, _) in fams["rt_esc_total"]["samples"]
        )

    def test_registry_values_round_trip(self):
        tm.counter("rt_c_total", {"op": "a"}, help="c").inc(3)
        tm.gauge("rt_g", help="g").set(2.5)
        fams = self._parse(tm.render_prometheus())
        assert fams["rt_c_total"]["samples"][
            ("rt_c_total", '{op="a"}')
        ] == 3
        assert fams["rt_g"]["samples"][("rt_g", "")] == 2.5
        assert fams["rt_c_total"]["type"] == "counter"
        assert fams["rt_g"]["type"] == "gauge"

    def test_family_total_sums_across_labels_and_histograms(self):
        tm.counter("rt_f_total", {"op": "a"}).inc(1)
        tm.counter("rt_f_total", {"op": "b"}).inc(2)
        h = tm.histogram("rt_f_seconds")
        h.observe(0.5)
        h.observe(1.5)
        assert tm.family_total("rt_f_total") == 3
        assert tm.family_total("rt_f_seconds") == pytest.approx(2.0)
        assert tm.family_total("rt_missing") == 0.0

    def test_live_registry_exposition_parses_after_a_fit(self, rng):
        """The whole live registry (every subsystem's families) must
        parse — the scrape-surface contract behind /metrics."""
        from oap_mllib_tpu.models.kmeans import KMeans

        x = rng.normal(size=(256, 4)).astype(np.float32)
        KMeans(k=2, max_iter=2, seed=0).fit(x)
        fams = self._parse(tm.render_prometheus())
        assert "oap_fit_total" in fams
        for name, fam in fams.items():
            assert "type" in fam and "help" in fam, name


class TestCounterAbsorption:
    """The pre-existing stats objects must mirror into the registry at
    their native increment points."""

    def setup_method(self):
        tm.reset()

    def test_progcache_feeds_registry(self):
        from oap_mllib_tpu.utils.progcache import ProgramCache

        pc = ProgramCache()
        pc.note("algoX", (1,))
        pc.note("algoX", (1,))
        pc.get_or_build("algoX", (2,), lambda: "prog")
        snap = tm.snapshot()
        assert snap["oap_progcache_misses_total"]["algo=algoX"] == 2
        assert snap["oap_progcache_hits_total"]["algo=algoX"] == 1

    def test_prefetch_feeds_registry(self):
        from oap_mllib_tpu.data.prefetch import Prefetcher, PrefetchStats

        stats = PrefetchStats()
        chunks = [np.zeros((16, 4), np.float32) for _ in range(3)]
        with Prefetcher(chunks, depth=2, stats=stats) as pf:
            list(pf)
        stats.finalize(None, "test_phase", wall=0.5)
        snap = tm.snapshot()
        assert snap["oap_prefetch_chunks_total"]["phase=test_phase"] == 3
        assert snap["oap_stream_rows_total"]["phase=test_phase"] == 48
        assert (
            snap["oap_stream_bytes_staged_total"]["phase=test_phase"]
            == 3 * 16 * 4 * 4
        )
        assert stats.bytes_staged == 3 * 16 * 4 * 4
        assert stats.rows == 48

    def test_resilience_feeds_registry(self):
        from oap_mllib_tpu.utils.resilience import ResilienceStats

        stats = ResilienceStats()
        stats.record("site", "transient", RuntimeError("x"))
        stats.note_retry(0.25)
        stats.note_degradation()
        snap = tm.snapshot()
        assert snap["oap_resilience_faults_total"]["kind=transient"] == 1
        assert snap["oap_resilience_retries_total"][""] == 1
        assert snap["oap_resilience_backoff_seconds_total"][""] == 0.25
        assert snap["oap_resilience_degradations_total"][""] == 1
        # the per-fit object kept its own view too
        assert stats.retries == 1 and stats.backoff_s == 0.25

    def test_collective_facade_feeds_registry_and_span(self, rng):
        import jax.numpy as jnp

        from oap_mllib_tpu.parallel.collective import allreduce_sum
        from oap_mllib_tpu.parallel.mesh import get_mesh

        x = jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32))
        sp = Span("phase")
        with enter(sp, annotate=False):
            allreduce_sum(x, get_mesh())
        snap = tm.snapshot()
        assert snap["oap_collective_ops_total"]["op=allreduce_sum"] == 1
        assert (
            snap["oap_collective_bytes_total"]["op=allreduce_sum"]
            == x.nbytes
        )
        assert sp.attrs["collectives"]["allreduce_sum"]["ops"] == 1


class TestFitSummaryTelemetry:
    def test_in_memory_fit_exposes_span_tree_and_metrics(self, rng):
        from oap_mllib_tpu import KMeans

        x = rng.normal(size=(256, 6)).astype(np.float32)
        m = KMeans(k=3, max_iter=3, seed=0).fit(x)
        tele = m.summary.telemetry
        assert tele["fit"] == "kmeans.fit"
        names = {c["name"] for c in tele["spans"]["children"]}
        assert {"table_convert", "init_centers", "lloyd_loop"} <= names
        assert tele["spans"]["duration_s"] > 0
        assert "oap_fit_total" in tele["metrics"]
        # the flat views still work off the same storage
        assert m.summary.timings.total() > 0

    def test_pca_and_streamed_fit_summaries(self, rng):
        from oap_mllib_tpu import PCA, KMeans
        from oap_mllib_tpu.data.stream import ChunkSource

        x = rng.normal(size=(400, 6)).astype(np.float32)
        p = PCA(k=2).fit(x)
        assert p.summary["telemetry"]["fit"] == "pca.fit"
        src = ChunkSource.from_array(x, chunk_rows=128)
        m = KMeans(k=3, max_iter=2, seed=0).fit(src)
        paths = {
            pth for pth, _ in
            _tree_paths(m.summary.telemetry["spans"])
        }
        assert "kmeans.fit/lloyd_loop/stage" in paths
        assert "kmeans.fit/lloyd_loop/compute" in paths


class TestCompatSurfaces:
    def test_drop_in_summary_exposes_telemetry(self, rng):
        """The compat layers proxy the inner summaries, so the span tree
        + metrics snapshot must reach unmodified user code through the
        drop-in surface too (the ISSUE 4 contract)."""
        from oap_mllib_tpu.compat import KMeans as CompatKMeans

        x = rng.normal(size=(256, 5)).astype(np.float32)
        m = CompatKMeans().setK(3).setSeed(1).fit({"features": x})
        assert m.summary.telemetry["fit"] == "kmeans.fit"
        assert "oap_fit_total" in m.summary.telemetry["metrics"]
        names = {
            c["name"] for c in m.summary.telemetry["spans"]["children"]
        }
        assert "lloyd_loop" in names


def _tree_paths(tree, prefix=""):
    path = prefix + tree["name"]
    yield path, tree
    for c in tree.get("children", []):
        yield from _tree_paths(c, path + "/")


class TestExporters:
    def test_jsonl_round_trip(self, rng, tmp_path):
        from oap_mllib_tpu import KMeans

        sink = tmp_path / "t.jsonl"
        set_config(telemetry_log=str(sink))
        x = rng.normal(size=(128, 4)).astype(np.float32)
        m = KMeans(k=2, max_iter=2, seed=0).fit(x)
        lines = sink.read_text().splitlines()
        assert lines
        records = [json.loads(ln) for ln in lines]  # every line parses
        spans = [r for r in records if r["type"] == "span"]
        metrics_recs = [r for r in records if r["type"] == "metrics"]
        assert len(metrics_recs) == 1
        assert all(r["rank"] == 0 for r in records)
        seqs = [r["seq"] for r in records]
        assert seqs == sorted(seqs)
        # the span records reproduce the summary tree exactly
        summary_paths = {
            p: n["duration_s"]
            for p, n in _tree_paths(m.summary.telemetry["spans"])
        }
        jsonl_paths = {r["path"]: r["duration_s"] for r in spans}
        assert jsonl_paths == summary_paths

    def test_multi_process_sink_is_rank_suffixed(self, tmp_path):
        from oap_mllib_tpu.telemetry.export import sink_path

        set_config(telemetry_log=str(tmp_path / "w.jsonl"))
        assert sink_path() == str(tmp_path / "w.jsonl")
        set_config(num_processes=4, process_id=3)
        assert sink_path() == str(tmp_path / "w.jsonl") + ".rank3"

    def test_report_renders_fit_and_process_views(self, rng):
        from oap_mllib_tpu import KMeans

        x = rng.normal(size=(128, 4)).astype(np.float32)
        m = KMeans(k=2, max_iter=2, seed=0).fit(x)
        text = telemetry.report(m.summary)
        assert "kmeans.fit" in text and "lloyd_loop" in text
        proc = telemetry.report()
        assert "process metrics" in proc

    def test_render_prometheus_reexport(self):
        tm.counter("oap_reexport_check_total").inc()
        assert "oap_reexport_check_total 1" in telemetry.render_prometheus()


class TestOrderedShutdown:
    """ISSUE 14 satellite: interpreter-exit work is ONE ordered hook —
    flight-recorder drain + final snapshot into the sink first, fleet
    endpoint teardown last — instead of independent atexit racers (the
    oaplint atexit-outside-shutdown rule keeps it unique)."""

    def test_shutdown_sequences_sink_before_server(self, tmp_path,
                                                   monkeypatch):
        from oap_mllib_tpu.telemetry import export, fleet

        order = []
        real_write = export._write_lines
        monkeypatch.setattr(
            export, "_write_lines",
            lambda path, recs: (order.append("sink"),
                                real_write(path, recs)),
        )
        monkeypatch.setattr(
            fleet, "stop_server", lambda: order.append("server"))
        set_config(telemetry_log=str(tmp_path / "s.jsonl"),
                   flight_recorder=32)
        from oap_mllib_tpu.telemetry import flightrec

        flightrec._reset_for_tests()  # a prior test's drain cursor
        flightrec.record("chunk", "probe", "#0")
        export.shutdown()
        assert order == ["sink", "server"]
        records = [json.loads(ln) for ln in
                   (tmp_path / "s.jsonl").read_text().splitlines()]
        kinds = [r["type"] for r in records]
        # the recorder tail and the final snapshot land in ONE batch,
        # drain first so post-mortem tooling sees a complete stream
        assert kinds == ["flightrec", "metrics"]
        assert all(r.get("final") for r in records)
        flightrec._reset_for_tests()

    def test_sink_failure_still_stops_the_server(self, tmp_path,
                                                 monkeypatch):
        from oap_mllib_tpu.telemetry import export, fleet

        stopped = []
        monkeypatch.setattr(
            fleet, "stop_server", lambda: stopped.append(True))
        monkeypatch.setattr(
            export, "_emit_final_snapshot",
            lambda: (_ for _ in ()).throw(RuntimeError("torn fs")),
        )
        with pytest.raises(RuntimeError):
            export.shutdown()
        assert stopped == [True]

    def test_register_shutdown_is_idempotent(self, monkeypatch):
        import atexit

        from oap_mllib_tpu.telemetry import export

        registered = []
        monkeypatch.setattr(
            atexit, "register", lambda fn: registered.append(fn))
        monkeypatch.setattr(export, "_shutdown_registered", False)
        export.register_shutdown()
        export.register_shutdown()
        assert registered == [export.shutdown]


class TestTelemetryOff:
    def test_no_sink_no_file(self, rng, tmp_path, monkeypatch):
        """With telemetry_log empty nothing is written anywhere and the
        fit still carries its summary telemetry (the in-memory layer is
        the accounting the summary always paid for)."""
        from oap_mllib_tpu import KMeans
        from oap_mllib_tpu.telemetry import export

        monkeypatch.chdir(tmp_path)
        calls = []
        monkeypatch.setattr(
            export, "_write_lines",
            lambda *a, **k: calls.append(a),
        )
        x = rng.normal(size=(128, 4)).astype(np.float32)
        m = KMeans(k=2, max_iter=2, seed=0).fit(x)
        assert calls == []  # sink off -> the writer is never invoked
        assert list(tmp_path.iterdir()) == []
        assert m.summary.telemetry["fit"] == "kmeans.fit"

    def test_span_annotation_guard_off_by_default(self):
        from oap_mllib_tpu.utils import profiling

        assert profiling.trace_active() is False

    def test_off_overhead_is_bounded(self, rng):
        """20 tiny fits with telemetry fully off: the span/registry layer
        must not dominate the fit wall.  This is a smoke bound (the real
        ≤2% gate is a bench comparison, not a unit test): the telemetry
        bookkeeping for a fit is a handful of dict ops, so 20 fits'
        TOTAL finalize+span cost must stay far under one fit's wall."""
        import time

        from oap_mllib_tpu import KMeans

        x = rng.normal(size=(64, 4)).astype(np.float32)
        KMeans(k=2, max_iter=2, seed=0).fit(x)  # warm compile
        t0 = time.perf_counter()
        for _ in range(20):
            KMeans(k=2, max_iter=2, seed=0).fit(x)
        fit_wall = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(2000):
            t = Timings("kmeans.fit")
            with phase_timer(t, "lloyd_loop"):
                pass
            telemetry.finalize_fit({"timings": t})
        tele_wall = (time.perf_counter() - t0) / 100  # per-20-fits cost
        assert tele_wall < max(0.02 * fit_wall, 0.005), (
            tele_wall, fit_wall
        )
