"""Resilience subsystem tests (utils/resilience.py, utils/faults.py).

Covers the tentpole contracts: the transient-error classifier, the
deterministic RetryPolicy, fault-registry determinism, each rung of the
degradation ladder (transient retry -> halved-chunk OOM retry -> CPU
fallback -> ResilienceError with history), the streamed numerical
guardrails, the bootstrap hardening, and fallback-vs-accelerated result
parity under ``device=cpu``.
"""

import time

import numpy as np
import pytest

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.utils import faults, resilience
from oap_mllib_tpu.utils.resilience import (
    NONFINITE,
    OOM,
    OOM_HOST,
    TRANSIENT,
    NonFiniteError,
    ResilienceError,
    ResilienceStats,
    RetryPolicy,
    classify_fault,
    halvings_available,
)


@pytest.fixture(autouse=True)
def _fast_retries():
    """Keep injected-fault tests snappy: near-zero backoff (the schedule
    logic is exercised either way), and a re-armed registry per test."""
    set_config(retry_backoff=0.001, retry_deadline=10.0)
    yield
    set_config(fault_spec="")
    faults.reset()


def _blobs(rng, n=600, d=6):
    proto = rng.normal(size=(3, d)).astype(np.float32) * 4.0
    return (proto[rng.integers(3, size=n)]
            + rng.normal(size=(n, d)).astype(np.float32) * 0.2)


class TestClassifier:
    def test_os_and_connection_errors_are_transient(self):
        assert classify_fault(OSError("disk hiccup")) == TRANSIENT
        assert classify_fault(ConnectionRefusedError("nope")) == TRANSIENT
        assert classify_fault(TimeoutError("slow")) == TRANSIENT
        assert classify_fault(RuntimeError("UNAVAILABLE: backend")) == TRANSIENT

    def test_oom_shapes(self):
        # the jaxlib XlaRuntimeError carries its status in the message —
        # the classifier must key on RESOURCE_EXHAUSTED textually
        assert classify_fault(
            RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating")
        ) == OOM
        assert classify_fault(
            RuntimeError("failed to allocate 16.00G")
        ) == OOM

    def test_host_oom_is_distinct_from_device_oom(self):
        """A bare MemoryError (a failed np allocation) is the HOST
        class — the spill rung — while device markers stay OOM (the
        halved-chunk rung); a MemoryError CARRYING a device marker is
        still device (jaxlib raises MemoryError subclasses for XLA
        RESOURCE_EXHAUSTED)."""
        assert classify_fault(MemoryError("host")) == OOM_HOST
        assert classify_fault(
            MemoryError("RESOURCE_EXHAUSTED: out of memory")
        ) == OOM

    def test_non_faults_are_none(self):
        assert classify_fault(ValueError("bad k")) is None
        assert classify_fault(TypeError("wrong arg")) is None
        assert classify_fault(KeyError("x")) is None

    def test_injected_faults_carry_their_kind(self):
        assert classify_fault(
            faults.InjectedTransientError("x")) == TRANSIENT
        assert classify_fault(faults.InjectedOOMError("x")) == OOM
        assert classify_fault(faults.InjectedHostOOMError("x")) == OOM_HOST
        assert classify_fault(faults.InjectedPermanentError("x")) is None

    def test_nonfinite(self):
        assert classify_fault(NonFiniteError("NaN centroids")) == NONFINITE


class TestRetryPolicy:
    def test_backoff_grows_and_caps(self):
        p = RetryPolicy(backoff_s=0.1, multiplier=2.0, max_backoff_s=0.5,
                        jitter=0.0)
        delays = [p.delay_s(i) for i in range(5)]
        assert delays[0] == pytest.approx(0.1)
        assert delays[1] == pytest.approx(0.2)
        assert delays[2] == pytest.approx(0.4)
        assert delays[3] == pytest.approx(0.5)  # capped
        assert delays == sorted(delays)

    def test_jitter_is_deterministic_and_site_dependent(self):
        p = RetryPolicy(backoff_s=0.1, jitter=0.5)
        a = p.delay_s(1, "stream.read")
        assert a == p.delay_s(1, "stream.read")  # reproducible
        assert a != p.delay_s(1, "fit.execute")  # de-synchronized
        base = RetryPolicy(backoff_s=0.1, jitter=0.0).delay_s(1)
        assert base <= a <= base * 1.5

    def test_run_with_retry_counts_and_gives_up(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        stats = ResilienceStats()
        out = resilience.run_with_retry(
            flaky, policy=RetryPolicy(backoff_s=0.001), stats=stats,
            site="t",
        )
        assert out == "ok" and stats.retries == 2 and stats.faults == 2

        stats = ResilienceStats()
        with pytest.raises(OSError):
            resilience.run_with_retry(
                lambda: (_ for _ in ()).throw(OSError("always")),
                policy=RetryPolicy(max_retries=2, backoff_s=0.001),
                stats=stats, site="t",
            )
        assert stats.retries == 2  # exhausted, then re-raised

    def test_run_with_retry_never_retries_non_faults(self):
        calls = []

        def bad():
            calls.append(1)
            raise ValueError("API misuse")

        with pytest.raises(ValueError):
            resilience.run_with_retry(bad, site="t")
        assert len(calls) == 1

    def test_deadline_bounds_wall(self):
        t0 = time.monotonic()
        with pytest.raises(OSError):
            resilience.run_with_retry(
                lambda: (_ for _ in ()).throw(OSError("always")),
                policy=RetryPolicy(
                    max_retries=100, backoff_s=0.2, deadline_s=0.3
                ),
                site="t",
            )
        assert time.monotonic() - t0 < 2.0


class TestFaultRegistry:
    def test_grammar_and_determinism(self):
        set_config(fault_spec="stream.read:fail=2")
        fired = []
        for i in range(5):
            try:
                faults.maybe_fault("stream.read")
                fired.append(False)
            except faults.InjectedTransientError:
                fired.append(True)
        # exactly the FIRST TWO calls fault — deterministic by call index
        assert fired == [True, True, False, False, False]
        st = faults.stats()["stream.read"]
        assert st["fired"] == 2 and st["calls"] == 5 and st["limit"] == 2

    def test_reset_restarts_counters(self):
        set_config(fault_spec="prefetch.stage:fail=1")
        with pytest.raises(faults.InjectedTransientError):
            faults.maybe_fault("prefetch.stage")
        faults.maybe_fault("prefetch.stage")  # budget spent
        faults.reset()
        with pytest.raises(faults.InjectedTransientError):
            faults.maybe_fault("prefetch.stage")  # budget restored

    def test_unarmed_sites_never_fire(self):
        set_config(fault_spec="stream.read:fail=99")
        faults.maybe_fault("fit.execute")
        faults.maybe_fault("prefetch.stage")

    def test_persistent_and_oom_kinds(self):
        set_config(fault_spec="fit.execute:oom=*")
        for _ in range(3):
            with pytest.raises(faults.InjectedOOMError, match="RESOURCE"):
                faults.maybe_fault("fit.execute")

    def test_spec_change_rearms(self):
        set_config(fault_spec="stream.read:fail=1")
        with pytest.raises(faults.InjectedTransientError):
            faults.maybe_fault("stream.read")
        set_config(fault_spec="")
        faults.maybe_fault("stream.read")  # disarmed by config change


class TestCheckpointFaultSites:
    """The ``ckpt.*`` fault sites (ISSUE 8 satellite): registry-level
    behavior here; the fit-level tiers (warn-never-kill writes, the
    corrupt-restore `resume` decision) are tests/test_checkpoint.py."""

    def test_sites_registered_and_grammar_accepts(self):
        assert "ckpt.write" in faults.SITES
        assert "ckpt.restore" in faults.SITES
        parsed = faults.parse_spec(
            "ckpt.write:fail=2,ckpt.restore:err=*"
        )
        assert parsed["ckpt.write"].limit == 2
        assert parsed["ckpt.restore"].limit == -1

    def test_all_kinds_fire_deterministically(self):
        for kind, exc in (
            ("fail", faults.InjectedTransientError),
            ("oom", faults.InjectedOOMError),
            ("err", faults.InjectedPermanentError),
            ("nan", faults.InjectedNonFiniteError),
        ):
            set_config(fault_spec=f"ckpt.write:{kind}=1")
            faults.reset()
            with pytest.raises(exc):
                faults.maybe_fault("ckpt.write")
            faults.maybe_fault("ckpt.write")  # budget spent: silent
            assert faults.stats()["ckpt.write"]["fired"] == 1

    def test_write_site_fault_never_escalates_the_ladder(self, rng):
        """A persistent ckpt.write fault must not consume ladder rungs:
        the fit completes accelerated with zero retries/degradations
        (checkpoint writes are insurance, outside the fault ladder)."""
        import tempfile

        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(
            checkpoint_dir=tempfile.mkdtemp(),
            fault_spec="ckpt.write:fail=*",
        )
        faults.reset()
        x = rng.normal(size=(600, 6)).astype(np.float32)
        m = KMeans(k=3, seed=1, max_iter=3).fit(
            ChunkSource.from_array(x, chunk_rows=256)
        )
        assert m.summary.accelerated
        assert m.summary.resilience["retries"] == 0
        assert m.summary.resilience["degradations"] == 0
        assert m.summary.checkpoint["writes"] == 0
        set_config(checkpoint_dir="")


class TestLadderVisibility:
    def test_stats_default_and_bypass_label(self):
        stats = ResilienceStats()
        assert stats.as_dict()["ladder"] == "active"
        out = resilience.resilient_fit(
            "t", lambda degraded: "ok", None, stats=stats
        )
        assert out == "ok"
        assert stats.ladder == "active"  # single-process world

    def test_bypass_label_when_world_large(self, monkeypatch):
        monkeypatch.setattr(resilience, "_world", lambda: 2)
        stats = ResilienceStats()
        resilience.resilient_fit(
            "t", lambda degraded: "ok", None, stats=stats
        )
        assert stats.ladder == "bypassed(static-world)"


class TestLadderRungs:
    """Each rung driven end to end through a real streamed K-Means fit."""

    def _fit(self, rng, **kw):
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _blobs(rng)
        src = ChunkSource.from_array(x, chunk_rows=128)
        return KMeans(k=3, seed=7, max_iter=8, **kw).fit(src)

    def test_transient_faults_absorbed_with_parity(self, rng):
        baseline = self._fit(rng)
        set_config(fault_spec="stream.read:fail=2,prefetch.stage:fail=1")
        faults.reset()
        m = self._fit(np.random.default_rng(42))
        res = m.summary.resilience
        assert res["retries"] == 3 and res["faults"] == 3
        assert res["degradations"] == 0
        assert m.summary.accelerated
        np.testing.assert_allclose(
            m.cluster_centers_, baseline.cluster_centers_, atol=1e-6
        )
        np.testing.assert_allclose(
            m.summary.training_cost, baseline.summary.training_cost,
            rtol=1e-6,
        )

    def test_oom_steps_to_halved_chunks_then_succeeds(self, rng):
        baseline = self._fit(rng)
        # exactly one OOM: the degraded (halved-chunk) retry completes
        set_config(fault_spec="fit.execute:oom=1")
        faults.reset()
        m = self._fit(np.random.default_rng(42))
        res = m.summary.resilience
        assert res["degradations"] == 1 and res["retries"] == 0
        assert m.summary.accelerated  # the DEGRADED rung, not fallback
        # halved chunks only re-block the passes; results match
        np.testing.assert_allclose(
            m.summary.training_cost, baseline.summary.training_cost,
            rtol=1e-5,
        )

    def test_persistent_oom_escalates_to_fallback(self, rng):
        set_config(fault_spec="fit.execute:oom=*", fallback=True)
        faults.reset()
        m = self._fit(rng)  # no user-visible exception
        assert not m.summary.accelerated  # CPU reference path ran
        res = m.summary.resilience
        assert res["degradations"] == 2  # halved-chunk rung + CPU rung
        assert len(res["history"]) == 2

    def test_fallback_disabled_raises_with_history(self, rng):
        set_config(fault_spec="fit.execute:oom=*", fallback=False)
        faults.reset()
        with pytest.raises(ResilienceError, match="fault history"):
            self._fit(rng)

    def test_permanent_injected_fault_propagates_unmasked(self, rng):
        set_config(fault_spec="stream.read:err=1")
        faults.reset()
        with pytest.raises(faults.InjectedPermanentError):
            self._fit(rng)

    def test_streamed_pca_absorbs_transients(self, rng):
        from oap_mllib_tpu.models.pca import PCA

        x = _blobs(rng)
        baseline = PCA(k=2).fit(ChunkSource.from_array(x, chunk_rows=128))
        set_config(fault_spec="stream.read:fail=1,prefetch.stage:fail=1")
        faults.reset()
        m = PCA(k=2).fit(ChunkSource.from_array(x, chunk_rows=128))
        assert m.summary["resilience"]["retries"] == 2
        np.testing.assert_allclose(
            m.explained_variance_, baseline.explained_variance_, atol=1e-6
        )
        np.testing.assert_allclose(
            np.abs(m.components_), np.abs(baseline.components_), atol=1e-6
        )

    def test_streamed_als_absorbs_transients(self, rng):
        from oap_mllib_tpu.models.als import ALS

        u = rng.integers(30, size=400).astype(np.float64)
        i = rng.integers(20, size=400).astype(np.float64)
        r = rng.random(400)
        tri = np.stack([u, i, r], axis=1)

        def fit():
            return ALS(rank=3, max_iter=2, seed=3).fit(
                ChunkSource.from_array(tri, chunk_rows=128)
            )

        baseline = fit()
        set_config(fault_spec="stream.read:fail=2,prefetch.stage:fail=1")
        faults.reset()
        m = fit()
        assert m.summary["resilience"]["retries"] == 3
        assert m.summary["accelerated"]
        np.testing.assert_allclose(
            m.user_factors_, baseline.user_factors_, atol=1e-6
        )
        np.testing.assert_allclose(
            m.item_factors_, baseline.item_factors_, atol=1e-6
        )

    def test_geometric_halving_walks_to_the_floor(self, rng):
        """chunk_rows=256 has TWO halvings above the 64-row floor
        (256 -> 128 -> 64): a persistent device OOM steps both, records
        the divisor trail in ``halvings``, then takes the CPU rung —
        the geometric generalization of the old single halved retry."""
        from oap_mllib_tpu.models.kmeans import KMeans

        assert halvings_available(256) == 2
        assert halvings_available(128) == 1
        assert halvings_available(64) == 1  # legacy single rung floor
        set_config(fault_spec="fit.execute:oom=*", fallback=True)
        faults.reset()
        x = _blobs(rng)
        m = KMeans(k=3, seed=7, max_iter=8).fit(
            ChunkSource.from_array(x, chunk_rows=256)
        )
        res = m.summary.resilience
        assert not m.summary.accelerated
        assert res["degradations"] == 3  # 2 halvings + the CPU rung
        assert res["halvings"] == [2, 4]
        assert len(res["history"]) == 3

    def test_halvings_bounded_by_retry_limit(self, rng):
        """retry_limit caps the geometric walk even with chunk headroom
        left (a fit must not halve forever on a huge chunk)."""
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(
            fault_spec="fit.execute:oom=*", fallback=True, retry_limit=1
        )
        faults.reset()
        x = _blobs(rng)
        m = KMeans(k=3, seed=7, max_iter=4).fit(
            ChunkSource.from_array(x, chunk_rows=512)
        )
        res = m.summary.resilience
        assert res["halvings"] == [2]  # one rung despite 3 of headroom
        assert res["degradations"] == 2
        set_config(retry_limit=5)

    def test_host_oom_spills_to_disk_and_completes(self, rng):
        """The spill rung: a host-classified OOM mid-pass stages the
        memory-backed source to a disk spill and the fit completes
        ACCELERATED through the streamed route, bit-identical to the
        clean run (the spill preserves rows, order, and chunking)."""
        from oap_mllib_tpu.models.kmeans import KMeans

        baseline = self._fit(rng)
        set_config(fault_spec="prefetch.stage:oomhost=1")
        faults.reset()
        m = self._fit(np.random.default_rng(42))
        res = m.summary.resilience
        assert res["spilled"] is True
        assert res["degradations"] == 1  # the spill rung only
        assert res["halvings"] == []
        assert m.summary.accelerated
        assert m.summary.route["spilled"] is True
        np.testing.assert_allclose(
            m.cluster_centers_, baseline.cluster_centers_, atol=1e-6
        )

    def test_failed_spill_falls_through_never_corrupts(self, rng, tmp_path):
        """A spill whose writes fault falls through the ladder (here to
        the halving rung, which absorbs the one-shot host OOM) — and
        the spill dir holds no committed spill, only ignorable tmp."""
        import os

        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(
            spill_dir=str(tmp_path),
            fault_spec="prefetch.stage:oomhost=1,spill.write:fail=*",
        )
        faults.reset()
        m = self._fit(rng)
        res = m.summary.resilience
        assert res["spilled"] is False  # the rung fired but failed
        assert m.summary.accelerated  # halving rung absorbed it
        committed = [
            f for f in os.listdir(tmp_path) if not f.endswith(".tmp")
            and os.path.getsize(os.path.join(tmp_path, f)) > 0
        ]
        assert committed == []
        set_config(spill_dir="")

    def test_disk_backed_sources_do_not_spill(self, rng, tmp_path):
        """A source already on disk has nothing to spill: a host OOM
        falls straight through to the halving rung."""
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _blobs(rng)
        path = str(tmp_path / "x.npy")
        np.save(path, x)
        set_config(fault_spec="prefetch.stage:oomhost=1")
        faults.reset()
        m = KMeans(k=3, seed=7, max_iter=8).fit(
            ChunkSource.from_npy(path, chunk_rows=128)
        )
        res = m.summary.resilience
        assert res["spilled"] is False
        assert res["halvings"] == [2]
        assert m.summary.accelerated

    def test_als_degraded_rung_matches(self, rng):
        """One OOM routes the ALS fit to the streamed kernels at halved
        blocks; factors must match the clean grouped fit (chunked
        segment-sums only reorder additions)."""
        from oap_mllib_tpu.models.als import ALS

        u = rng.integers(30, size=400)
        i = rng.integers(20, size=400)
        r = rng.random(400).astype(np.float32)
        baseline = ALS(rank=3, max_iter=2, seed=3).fit(u, i, r)
        set_config(fault_spec="fit.execute:oom=1")
        faults.reset()
        m = ALS(rank=3, max_iter=2, seed=3).fit(u, i, r)
        assert m.summary["resilience"]["degradations"] == 1
        assert m.summary["accelerated"]
        np.testing.assert_allclose(
            m.user_factors_, baseline.user_factors_, atol=2e-5, rtol=2e-5
        )


class TestNumericalGuardrails:
    def test_kmeans_nan_data_raises_by_default(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _blobs(rng, n=256)
        x[7, 2] = np.nan
        src = ChunkSource.from_array(x, chunk_rows=64)
        with pytest.raises(NonFiniteError, match="centroids"):
            KMeans(k=3, seed=1, max_iter=3, init_mode="random").fit(src)

    def test_pca_overflow_gram_detected(self, rng):
        """f32 Gram overflow (x ~ 3e19 squares past f32 max) must trip
        the Gram-pass guardrail, not silently produce Inf components."""
        from oap_mllib_tpu.models.pca import PCA

        x = (rng.normal(size=(256, 4)) * 3e19).astype(np.float32)
        src = ChunkSource.from_array(x, chunk_rows=64)
        with pytest.raises(NonFiniteError, match="Gram"):
            PCA(k=2).fit(src)

    def test_pca_overflow_falls_back_when_configured(self, rng):
        """nonfinite_policy="fallback": the same overflow degrades to the
        f64 NumPy path, which handles the magnitudes fine."""
        from oap_mllib_tpu.models.pca import PCA

        set_config(nonfinite_policy="fallback")
        x = (rng.normal(size=(256, 4)) * 3e19).astype(np.float32)
        src = ChunkSource.from_array(x, chunk_rows=64)
        m = PCA(k=2).fit(src)
        assert not m.summary["accelerated"]
        assert np.all(np.isfinite(m.components_))
        assert m.summary["resilience"]["degradations"] == 1

    def test_nonfinite_raise_beats_fallback_config(self, rng):
        """policy="raise" surfaces the NonFiniteError even when
        Config.fallback would allow degrading — masking NaNs behind a
        CPU rerun is exactly what the knob exists to prevent."""
        from oap_mllib_tpu.models.kmeans import KMeans

        set_config(nonfinite_policy="raise", fallback=True)
        x = _blobs(rng, n=256)
        x[3, 0] = np.inf
        src = ChunkSource.from_array(x, chunk_rows=64)
        with pytest.raises(NonFiniteError):
            KMeans(k=3, seed=1, max_iter=3, init_mode="random").fit(src)


class TestBootstrapHardening:
    def test_nonzero_rank_error_names_env_seen(self, monkeypatch):
        from oap_mllib_tpu.parallel import bootstrap

        monkeypatch.delenv(
            "OAP_MLLIB_TPU_COORDINATOR_ADDRESS", raising=False
        )
        set_config(num_processes=2, process_id=1, coordinator_address="")
        with pytest.raises(ValueError) as ei:
            bootstrap.initialize_distributed()
        msg = str(ei.value)
        assert "OAP_MLLIB_TPU_COORDINATOR_ADDRESS=None" in msg
        assert "process_id=1" in msg and "num_processes=2" in msg

    def test_connect_retries_under_budget(self, monkeypatch):
        """bootstrap.connect transient faults retry with backoff; the
        stubbed initialize then succeeds on the third attempt."""
        import jax

        from oap_mllib_tpu.parallel import bootstrap

        calls = []
        monkeypatch.setattr(
            jax.distributed, "initialize",
            lambda **kw: calls.append(kw),
        )
        monkeypatch.setattr(bootstrap, "_initialized", False)
        set_config(
            fault_spec="bootstrap.connect:fail=2", bootstrap_timeout=30.0
        )
        faults.reset()
        assert bootstrap.initialize_distributed(
            "127.0.0.1:9999", num_processes=2, process_id=0
        )
        assert len(calls) == 1  # two faulted attempts never reached jax
        monkeypatch.setattr(bootstrap, "_initialized", False)

    def test_connect_timeout_names_coordinator_rank_elapsed(
        self, monkeypatch
    ):
        from oap_mllib_tpu.parallel import bootstrap

        monkeypatch.setattr(bootstrap, "_initialized", False)
        set_config(
            fault_spec="bootstrap.connect:fail=*", bootstrap_timeout=0.05
        )
        faults.reset()
        with pytest.raises(RuntimeError) as ei:
            bootstrap.initialize_distributed(
                "10.9.9.9:321", num_processes=4, process_id=2
            )
        msg = str(ei.value)
        assert "10.9.9.9:321" in msg
        assert "rank=2/4" in msg
        assert "bootstrap_timeout" in msg

    def test_free_port_returns_bindable_port(self):
        import socket

        from oap_mllib_tpu.parallel.bootstrap import free_port

        p = free_port("127.0.0.1", 23000)
        assert p >= 23000
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s.bind(("127.0.0.1", p))
        finally:
            s.close()


class TestFallbackParity:
    """device=cpu forces the NumPy reference path; its results must
    agree with the accelerated (XLA-on-CPU) path on small fixtures —
    the contract that makes the ladder's final rung a safe landing."""

    def test_kmeans_cost_parity(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _blobs(rng)
        acc = KMeans(k=3, seed=7, max_iter=25).fit(x)
        assert acc.summary.accelerated
        set_config(device="cpu")
        fb = KMeans(k=3, seed=7, max_iter=25).fit(x)
        assert not fb.summary.accelerated
        # different init RNG streams, same well-separated optimum
        np.testing.assert_allclose(
            fb.summary.training_cost, acc.summary.training_cost, rtol=1e-3
        )

    def test_pca_parity(self, rng):
        from oap_mllib_tpu.models.pca import PCA

        x = rng.normal(size=(400, 8)).astype(np.float32) @ np.diag(
            [5, 4, 3, 2, 1, 0.5, 0.2, 0.1]
        ).astype(np.float32)
        acc = PCA(k=3).fit(x)
        assert acc.summary["accelerated"]
        set_config(device="cpu")
        fb = PCA(k=3).fit(x)
        assert not fb.summary["accelerated"]
        np.testing.assert_allclose(
            fb.explained_variance_, acc.explained_variance_, atol=1e-4
        )
        np.testing.assert_allclose(
            np.abs(fb.components_), np.abs(acc.components_), atol=1e-3
        )

    def test_als_factor_parity_with_shared_init(self, rng):
        from oap_mllib_tpu.fallback import als_np
        from oap_mllib_tpu.models.als import ALS

        nu, ni, rank = 25, 18, 3
        u = rng.integers(nu, size=500)
        i = rng.integers(ni, size=500)
        u[0], i[0] = nu - 1, ni - 1
        r = rng.random(500).astype(np.float32) * 4 + 1
        init = (
            als_np.init_factors(nu, rank, 3),
            als_np.init_factors(ni, rank, 4),
        )
        acc = ALS(rank=rank, max_iter=3, seed=3).fit(u, i, r, init=init)
        assert acc.summary["accelerated"]
        set_config(device="cpu")
        fb = ALS(rank=rank, max_iter=3, seed=3).fit(u, i, r, init=init)
        assert not fb.summary["accelerated"]
        np.testing.assert_allclose(
            fb.user_factors_, acc.user_factors_, atol=2e-3, rtol=2e-3
        )
        np.testing.assert_allclose(
            fb.item_factors_, acc.item_factors_, atol=2e-3, rtol=2e-3
        )


class TestStatsSurface:
    def test_summaries_carry_resilience_next_to_progcache(self, rng):
        """Every accelerated fit summary reports the resilience counters
        beside the progcache delta — the observability contract."""
        from oap_mllib_tpu.models.kmeans import KMeans
        from oap_mllib_tpu.models.pca import PCA

        x = _blobs(rng, n=300)
        km = KMeans(k=3, seed=1, max_iter=3).fit(x)
        assert hasattr(km.summary, "progcache")
        assert km.summary.resilience["faults"] == 0
        pc = PCA(k=2).fit(x)
        assert "progcache" in pc.summary and "resilience" in pc.summary

    def test_merge_stats_handles_both_summary_shapes(self):
        stats = ResilienceStats()
        stats.retries = 2
        d = {}
        resilience.merge_stats(d, stats)
        assert d["resilience"]["retries"] == 2

        class S:
            pass

        s = S()
        resilience.merge_stats(s, stats)
        assert s.resilience["retries"] == 2
