"""Native runtime layer tests: parsers vs Python oracles, table store
lifecycle, bootstrap probing, shuffle prep.

The reference had NO native unit tests (survey §4 "fixtures/mocks: none") —
this suite adds the coverage the survey takeaway calls for.  Tests skip if
no C++ toolchain is present (the NumPy fallbacks are covered either way via
the OAP_MLLIB_TPU_PURE_PYTHON_IO path in test_io.py).
"""

import ctypes
import os

import numpy as np
import pytest

from oap_mllib_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no toolchain)"
)

HERE = os.path.dirname(os.path.abspath(__file__))
DATA = os.path.join(HERE, "..", "examples", "data")


class TestParsers:
    def test_libsvm_matches_python(self):
        from oap_mllib_tpu.data import io as io_mod

        path = os.path.join(DATA, "sample_kmeans_data.txt")
        nl, nx = native.parse_libsvm(path)
        # python oracle: bypass native
        labels, x = [], []
        with open(path) as f:
            for line in f:
                parts = line.split()
                labels.append(float(parts[0]))
                row = {}
                for tok in parts[1:]:
                    k, v = tok.split(":")
                    row[int(k)] = float(v)
                x.append(row)
        d = max(max(r) for r in x)
        px = np.zeros((len(x), d))
        for i, r in enumerate(x):
            for k, v in r.items():
                px[i, k - 1] = v
        np.testing.assert_array_equal(nx, px)
        np.testing.assert_array_equal(nl, labels)

    def test_csv_matches_numpy(self):
        path = os.path.join(DATA, "pca_data.csv")
        nx = native.parse_csv(path)
        px = np.loadtxt(path, delimiter=",", ndmin=2)
        np.testing.assert_allclose(nx, px, atol=0)

    def test_ratings_matches_python(self):
        path = os.path.join(DATA, "sample_als_ratings.txt")
        nu, ni, nr = native.parse_ratings(path)
        pu, pi, pr = [], [], []
        with open(path) as f:
            for line in f:
                a, b, c = line.strip().split("::")
                pu.append(int(a)); pi.append(int(b)); pr.append(float(c))
        np.testing.assert_array_equal(nu, pu)
        np.testing.assert_array_equal(ni, pi)
        np.testing.assert_array_equal(nr, np.asarray(pr, np.float32))

    def test_malformed_libsvm_raises(self, tmp_path):
        p = tmp_path / "bad.txt"
        p.write_text("1.0 not_a_token\n")
        with pytest.raises(ValueError):
            native.parse_libsvm(str(p))

    def test_ragged_csv_raises(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("1,2,3\n4,5\n")
        with pytest.raises(ValueError):
            native.parse_csv(str(p))

    def test_missing_file_raises(self):
        with pytest.raises(ValueError):
            native.parse_csv("/nonexistent/file.csv")


class TestTableStore:
    def test_create_append_copyout_free(self):
        lib = native._load()
        before = lib.oap_table_count()
        h = lib.oap_table_create(2, 3)
        assert h > 0
        batch = np.arange(6, dtype=np.float64)
        p = batch.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
        assert lib.oap_table_append(h, p, 2) == 2
        # growth past capacity
        assert lib.oap_table_append(h, p, 2) == 4
        assert lib.oap_table_rows(h) == 4
        assert lib.oap_table_cols(h) == 3
        out = np.empty((4, 3))
        got = lib.oap_table_copy_out(
            h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), 4)
        assert got == 4
        np.testing.assert_array_equal(out[:2].ravel(), batch)
        np.testing.assert_array_equal(out[2:].ravel(), batch)
        assert lib.oap_table_free(h) == 0
        assert lib.oap_table_count() == before  # no leak

    def test_merge(self):
        lib = native._load()
        a = lib.oap_table_create(1, 2)
        b = lib.oap_table_create(1, 2)
        r1 = np.array([1.0, 2.0])
        r2 = np.array([3.0, 4.0])
        f64p = ctypes.POINTER(ctypes.c_double)
        lib.oap_table_append(a, r1.ctypes.data_as(f64p), 1)
        lib.oap_table_append(b, r2.ctypes.data_as(f64p), 1)
        assert lib.oap_table_merge(a, b) == 2
        out = np.empty((2, 2))
        lib.oap_table_copy_out(a, out.ctypes.data_as(f64p), 2)
        np.testing.assert_array_equal(out, [[1, 2], [3, 4]])
        lib.oap_table_free(a)
        # src was consumed
        assert lib.oap_table_rows(b) == -1

    def test_bad_handle(self):
        lib = native._load()
        assert lib.oap_table_rows(999999) == -1
        assert lib.oap_table_free(999999) == -1


class TestNetProbe:
    def test_local_ip_format(self):
        ip = native.local_ip()
        if ip is None:
            pytest.skip("no non-loopback interface")
        parts = ip.split(".")
        assert len(parts) == 4 and all(0 <= int(p) <= 255 for p in parts)
        assert not ip.startswith("127.")

    def test_free_port_bindable(self):
        import socket

        port = native.free_port(start=39000)
        assert port is not None and 39000 <= port <= 65535
        s = socket.socket()
        s.bind(("", port))  # should succeed right after probe
        s.close()


class TestShuffle:
    def test_prep_matches_numpy(self, rng):
        n = 500
        u = rng.integers(0, 40, n)
        i = rng.integers(0, 30, n)
        r = rng.random(n).astype(np.float32)
        us, it, rs, counts, perm = native.shuffle_prep(u, i, r, 10, 4)
        block = np.minimum(u // 10, 3)
        pperm = np.lexsort((i, u, block))
        np.testing.assert_array_equal(us, u[pperm])
        np.testing.assert_array_equal(it, i[pperm])
        np.testing.assert_array_equal(counts, np.bincount(block, minlength=4))
        assert counts.sum() == n

    def test_distinct_count(self):
        assert native.distinct_count(np.array([1, 1, 2, 5, 5, 9])) == 4
        assert native.distinct_count(np.array([], dtype=np.int64)) == 0


class TestReviewRegressions:
    def test_shuffle_zero_block_size_raises(self, rng):
        with pytest.raises(ValueError):
            native.shuffle_prep(
                np.array([1]), np.array([1]), np.array([1.0]), 0, 4)
        with pytest.raises(ValueError):
            native.shuffle_prep(
                np.array([1]), np.array([1]), np.array([1.0]), 10, 0)

    def test_csv_bad_cell_no_leak(self, tmp_path):
        lib = native._load()
        before = lib.oap_table_count()
        p = tmp_path / "bad2.csv"
        p.write_text("1,2\n3,x\n")
        with pytest.raises(ValueError):
            native.parse_csv(str(p))
        assert lib.oap_table_count() == before

    def test_csv_wrong_delimiter_rejected(self, tmp_path):
        p = tmp_path / "ws.csv"
        p.write_text("1.0 2.0\n")
        with pytest.raises(ValueError):
            native.parse_csv(str(p), ",")

    def test_libsvm_index_beyond_n_features_errors_both_paths(self, tmp_path, monkeypatch):
        p = tmp_path / "over.txt"
        p.write_text("1.0 1:1.0 3:1.5\n")
        with pytest.raises(ValueError):
            native.parse_libsvm(str(p), 2)
        from oap_mllib_tpu.data import io as io_mod
        monkeypatch.setenv("OAP_MLLIB_TPU_PURE_PYTHON_IO", "1")
        with pytest.raises(ValueError):
            io_mod.read_libsvm(str(p), n_features=2)

    def test_merge_self_rejected(self):
        lib = native._load()
        h = lib.oap_table_create(1, 2)
        assert lib.oap_table_merge(h, h) == -1
        lib.oap_table_free(h)

    def test_csv_comment_lines_match_loadtxt(self, tmp_path):
        p = tmp_path / "c.csv"
        p.write_text("# header comment\n1,2\n# mid comment\n3,4\n")
        nx = native.parse_csv(str(p))
        px = np.loadtxt(str(p), delimiter=",", ndmin=2)
        np.testing.assert_array_equal(nx, px)

    def test_ratings_reject_float_ids_and_garbage(self, tmp_path):
        for bad in ("1.5::2::3\n", "1::2::3junk\n"):
            p = tmp_path / "bad_r.txt"
            p.write_text(bad)
            with pytest.raises(ValueError):
                native.parse_ratings(str(p))

    def test_table_view_zero_copy(self):
        lib = native._load()
        h = lib.oap_table_create(1, 2)
        row = np.array([5.0, 6.0])
        lib.oap_table_append(h, row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), 1)
        view = native.table_view(h)
        np.testing.assert_array_equal(view, [[5.0, 6.0]])
        view[0, 0] = 7.0  # writes through — same memory
        out = np.empty((1, 2))
        lib.oap_table_copy_out(h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), 1)
        assert out[0, 0] == 7.0
        lib.oap_table_free(h)


class TestGroupedPrep:
    def test_grouped_build_matches_numpy(self, rng, monkeypatch):
        """Native counting-sort grouped-edge build is bit-identical to the
        NumPy argsort path (incl. the padded-total guard)."""
        from oap_mllib_tpu import native
        from oap_mllib_tpu.ops import als_ops

        if not native.available():
            pytest.skip("native library unavailable")
        nnz, n_dst = 5000, 120
        dst = rng.integers(n_dst, size=nnz).astype(np.int64)
        src = rng.integers(300, size=nnz).astype(np.int64)
        conf = rng.random(nnz).astype(np.float32)
        nat = als_ops.build_grouped_edges(dst, src, conf, n_dst, group_size=16)
        monkeypatch.setenv("OAP_MLLIB_TPU_PURE_PYTHON_IO", "1")
        ref = als_ops.build_grouped_edges(dst, src, conf, n_dst, group_size=16)
        for a, b in zip(nat, ref):
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(a, b)
        monkeypatch.delenv("OAP_MLLIB_TPU_PURE_PYTHON_IO")
        assert als_ops.grouped_padded_edges(dst, n_dst, 16) == nat[0].size

    def test_grouped_build_out_of_range_raises(self):
        from oap_mllib_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        with pytest.raises(ValueError, match="out of range"):
            native.als_grouped_total(np.asarray([0, 7], np.int64), 5, 8)
