"""Spark-ML-style compat API tests: the builder/DataFrame surface a
reference (Spark ML / PySpark) user migrates to — modeled on how the
reference's suites drive estimators through the Spark API
(IntelKMeansSuite "default params" / "fit & transform" patterns)."""

import numpy as np
import pytest

from oap_mllib_tpu.compat import ALS, KMeans, PCA


def _df(rng, n=300, d=6, k=3):
    centers = rng.normal(size=(k, d)) * 5
    x = centers[rng.integers(k, size=n)] + rng.normal(size=(n, d)) * 0.05
    return {"features": x}


class TestKMeansCompat:
    def test_default_params(self):
        km = KMeans()
        assert km.getK() == 2
        assert km.getMaxIter() == 20
        assert km.getInitMode() == "k-means||"
        assert km.getDistanceMeasure() == "euclidean"
        assert km.getFeaturesCol() == "features"
        assert km.getPredictionCol() == "prediction"

    def test_builder_chain_fit_transform(self, rng):
        df = _df(rng)
        model = (
            KMeans().setK(3).setMaxIter(30).setTol(1e-6).setSeed(7).fit(df)
        )
        assert model.clusterCenters().shape == (3, 6)
        out = model.transform(df)
        assert "prediction" in out and out["prediction"].shape == (300,)
        assert "features" in out  # input column preserved
        assert "prediction" not in df  # input not mutated
        assert model.summary.num_iter >= 1

    def test_custom_columns_and_weights(self, rng):
        x = np.array([[0.0, 0.0], [10.0, 10.0]])
        df = {"f": x, "w": np.array([3.0, 1.0])}
        model = (
            KMeans().setK(1).setMaxIter(5).setFeaturesCol("f")
            .setWeightCol("w").setPredictionCol("cluster").fit(df)
        )
        np.testing.assert_allclose(model.clusterCenters()[0], [2.5, 2.5], atol=1e-4)
        out = model.transform(df)
        assert "cluster" in out

    def test_single_vector_predict(self, rng):
        df = _df(rng)
        model = KMeans().setK(3).setSeed(1).fit(df)
        p = model.predict(df["features"][0])
        assert isinstance(p, int) and 0 <= p < 3

    def test_missing_column_raises(self, rng):
        with pytest.raises(KeyError):
            KMeans().setFeaturesCol("nope").fit(_df(rng))

    def test_save_load(self, tmp_path, rng):
        df = _df(rng)
        model = KMeans().setK(3).setSeed(1).fit(df)
        model.save(str(tmp_path / "m"))
        from oap_mllib_tpu.compat.spark import KMeansModel

        loaded = KMeansModel.load(str(tmp_path / "m"))
        np.testing.assert_array_equal(loaded.clusterCenters(), model.clusterCenters())

    def test_save_load_keeps_columns(self, tmp_path, rng):
        """Column config survives persistence (the round-4 ALS fix,
        applied to every compat model): a loaded model transforms frames
        with the SAME custom columns the fitted one did."""
        from oap_mllib_tpu.compat.spark import KMeansModel, PCAModel

        x = rng.normal(size=(60, 5))
        km = (KMeans().setK(2).setSeed(1)
              .setFeaturesCol("f").setPredictionCol("lbl")
              .fit({"f": x}))
        km.save(str(tmp_path / "km"))
        lk = KMeansModel.load(str(tmp_path / "km"))
        out = lk.transform({"f": x})
        assert "lbl" in out
        pm = (PCA().setK(2).setInputCol("f").setOutputCol("proj")
              .fit({"f": x}))
        pm.save(str(tmp_path / "pca"))
        lp = PCAModel.load(str(tmp_path / "pca"))
        assert "proj" in lp.transform({"f": x})


class TestPCACompat:
    def test_fit_transform(self, rng):
        df = _df(rng, d=8)
        model = PCA().setK(3).setOutputCol("pca").fit(df)
        assert model.pc.shape == (8, 3)
        assert model.explainedVariance.shape == (3,)
        out = model.transform(df)
        assert out["pca"].shape == (300, 3)

    def test_unset_k_raises(self, rng):
        with pytest.raises(ValueError):
            PCA().fit(_df(rng))


class TestALSCompat:
    def _ratings_df(self, rng):
        mask = rng.random((30, 20)) < 0.3
        u, i = np.nonzero(mask)
        return {
            "user": u, "item": i,
            "rating": rng.integers(1, 6, len(u)).astype(np.float32),
        }

    def test_implicit_fit_transform(self, rng):
        df = self._ratings_df(rng)
        model = (
            ALS().setRank(6).setMaxIter(4).setRegParam(0.1).setAlpha(2.0)
            .setImplicitPrefs(True).fit(df)
        )
        assert model.rank == 6
        assert model.userFactors.shape[1] == 6
        out = model.transform(df)
        assert "prediction" in out and len(out["prediction"]) == len(df["user"])

    def test_recommend_both_directions(self, rng):
        df = self._ratings_df(rng)
        model = ALS().setRank(4).setMaxIter(2).setImplicitPrefs(True).fit(df)
        ru = model.recommendForAllUsers(5)
        ri = model.recommendForAllItems(5)
        assert ru.shape[1] == 5 and ri.shape[1] == 5
        assert ru.max() < model.itemFactors.shape[0]
        assert ri.max() < model.userFactors.shape[0]

    def test_recommend_subsets_distinct_and_join(self, rng):
        """recommendForUserSubset / ItemSubset: DISTINCT the id column,
        drop ids without a trained factor row (Spark's join semantics,
        ALS.scala:379-429) — never an error for unseen ids — and return
        rows aligned with the surviving ids."""
        df = self._ratings_df(rng)
        model = ALS().setRank(4).setMaxIter(2).setImplicitPrefs(True).fit(df)
        nu = model.userFactors.shape[0]
        all_recs = model.recommendForAllUsers(5)
        subset = {"user": np.array([7, 2, 7, 999, 2])}  # dupes + unseen
        ids, recs = model.recommendForUserSubset(subset, 5)
        np.testing.assert_array_equal(ids, [2, 7])  # distinct, joined
        np.testing.assert_array_equal(recs, all_recs[[2, 7]])
        # withScores rides along; bare id arrays accepted too
        ids2, recs2, scores = model.recommendForItemSubset(
            np.array([1, 3]), 4, withScores=True
        )
        np.testing.assert_array_equal(ids2, [1, 3])
        assert recs2.shape == scores.shape == (2, 4)
        assert recs2.max() < nu
        # every id unseen: empty result, not an error
        ids3, recs3 = model.recommendForUserSubset(
            {"user": np.array([990, 991])}, 5
        )
        assert len(ids3) == 0 and recs3.shape == (0, 5)

    def test_model_setters_post_fit(self, rng):
        """Spark's fitted models re-expose their column/strategy params
        as setters: a loaded ALSModel can switch nan<->drop or be
        re-pointed at different columns without refitting."""
        df = self._ratings_df(rng)
        model = ALS().setRank(3).setMaxIter(2).fit(df)  # default "nan"
        probe = {"user": np.array([0, 999]), "item": np.array([0, 1]),
                 "rating": np.array([1.0, 2.0], np.float32)}
        out = model.transform(probe)
        assert len(out["prediction"]) == 2 and np.isnan(out["prediction"][1])
        model.setColdStartStrategy("drop").setPredictionCol("score")
        out2 = model.transform(probe)
        assert "score" in out2 and len(out2["score"]) == 1
        assert np.isfinite(out2["score"]).all()
        with pytest.raises(ValueError, match="coldStartStrategy"):
            model.setColdStartStrategy("explode")
        # column re-pointing: same data under different names
        model.setUserCol("u2").setItemCol("i2")
        out3 = model.transform({"u2": probe["user"], "i2": probe["item"]})
        np.testing.assert_allclose(out3["score"], out2["score"])

    def test_ndarray_input_rejected(self):
        with pytest.raises(TypeError):
            ALS().fit(np.zeros((3, 3)))

    def test_default_params_match_spark(self):
        """Spark's setDefault block (reference ALS.scala:241-245)."""
        als = ALS()
        assert als.getRank() == 10
        assert als.getMaxIter() == 10
        assert als.getRegParam() == 0.1
        assert als.getNumUserBlocks() == 10
        assert als.getNumItemBlocks() == 10
        assert als.getImplicitPrefs() is False
        assert als.getAlpha() == 1.0
        assert als.getNonnegative() is False
        assert als.getCheckpointInterval() == 10
        assert als.getColdStartStrategy() == "nan"
        assert als.getPredictionCol() == "prediction"

    def test_num_blocks_params(self, rng):
        als = ALS().setNumUserBlocks(3).setNumItemBlocks(5)
        assert als.getNumUserBlocks() == 3 and als.getNumItemBlocks() == 5
        als.setNumBlocks(2)  # sets both (ALS.scala:679-683)
        assert als.getNumUserBlocks() == 2 and als.getNumItemBlocks() == 2
        with pytest.raises(ValueError):
            ALS().setNumUserBlocks(0)
        with pytest.raises(ValueError):
            ALS().setNumItemBlocks(-1)
        df = self._ratings_df(rng)
        model = als.setRank(3).setMaxIter(2).setImplicitPrefs(True).fit(df)
        # the requested hint is recorded and the effective user-block
        # count (mesh data-axis size) is capped by it
        summary = model._inner.summary
        assert summary["num_user_blocks_requested"] == 2
        assert summary["num_item_blocks_requested"] == 2
        assert summary["num_user_blocks"] <= 2

    def test_default_num_blocks_not_forwarded(self, rng):
        """Spark's numUserBlocks=10 default is a partitioning default, not
        a device cap — an untouched builder must not cap the mesh."""
        df = self._ratings_df(rng)
        model = ALS().setRank(3).setMaxIter(2).setImplicitPrefs(True).fit(df)
        summary = model._inner.summary
        assert "num_user_blocks_requested" not in summary

    def test_num_user_blocks_with_model_parallel(self, rng):
        """The cap counts user blocks (data-axis slots), not raw devices:
        with model_parallel=2 a 3-block cap needs 6 devices."""
        from oap_mllib_tpu.config import set_config

        set_config(model_parallel=2)
        from oap_mllib_tpu import ALS as CoreALS

        df = self._ratings_df(rng)
        m = CoreALS(rank=3, max_iter=2, implicit_prefs=True,
                    num_user_blocks=3).fit(df["user"], df["item"], df["rating"])
        assert m.summary["num_user_blocks"] == 3

    def test_cold_start_in_range_unseen_id(self, rng):
        """Ids inside the dense id range whose every rating fell outside
        the training split are still cold (Spark: unseen-in-training)."""
        df = self._ratings_df(rng)
        # remove every rating of user 3 from training
        keep = df["user"] != 3
        train = {k: v[keep] for k, v in df.items()}
        model = (
            ALS().setRank(3).setMaxIter(2).setImplicitPrefs(True)
            .fit(train)
        )
        test = {"user": np.array([0, 3]), "item": np.array([0, 0]),
                "rating": np.array([1.0, 1.0], np.float32)}
        out = model.transform(test)
        assert np.isfinite(out["prediction"][0])
        assert np.isnan(out["prediction"][1])
        dropped = (
            ALS().setRank(3).setMaxIter(2).setImplicitPrefs(True)
            .setColdStartStrategy("drop").fit(train).transform(test)
        )
        np.testing.assert_array_equal(dropped["user"], [0])

    def test_cold_start_nan(self, rng):
        df = self._ratings_df(rng)
        model = ALS().setRank(3).setMaxIter(2).setImplicitPrefs(True).fit(df)
        n_users = model.userFactors.shape[0]
        test = {"user": np.array([0, n_users + 7]), "item": np.array([0, 1]),
                "rating": np.array([1.0, 1.0], np.float32)}
        out = model.transform(test)
        assert len(out["prediction"]) == 2
        assert np.isfinite(out["prediction"][0])
        assert np.isnan(out["prediction"][1])

    def test_cold_start_drop(self, rng):
        df = self._ratings_df(rng)
        model = (
            ALS().setRank(3).setMaxIter(2).setImplicitPrefs(True)
            .setColdStartStrategy("drop").fit(df)
        )
        n_items = model.itemFactors.shape[0]
        test = {"user": np.array([0, 1, 2]),
                "item": np.array([0, n_items + 3, 1]),
                "rating": np.array([1.0, 2.0, 3.0], np.float32)}
        out = model.transform(test)
        # cold row removed from EVERY column, predictions all finite
        assert len(out["prediction"]) == 2
        assert np.isfinite(out["prediction"]).all()
        np.testing.assert_array_equal(out["user"], [0, 2])
        np.testing.assert_array_equal(out["rating"], [1.0, 3.0])

    def test_cold_start_validation(self):
        with pytest.raises(ValueError):
            ALS().setColdStartStrategy("bogus")
        # case-insensitive like the Spark param validator (ALS.scala:125-128)
        assert ALS().setColdStartStrategy("DROP").getColdStartStrategy() == "drop"

    def test_cold_start_survives_save_load(self, tmp_path, rng):
        """save/load persists the seen-id sets, coldStartStrategy, and
        column names (Spark ALSModel persistence, ALS.scala:119-128) —
        an in-range-but-unseen id must still be cold on a LOADED model
        (round-3 loads silently degraded to range checks)."""
        from oap_mllib_tpu.compat.spark import ALSModel as CompatALSModel

        df = self._ratings_df(rng)
        keep = df["user"] != 3  # user 3: in-range, unseen in training
        train = {k: v[keep] for k, v in df.items()}
        model = (
            ALS().setRank(3).setMaxIter(2).setImplicitPrefs(True)
            .setColdStartStrategy("drop").setPredictionCol("p")
            .fit(train)
        )
        path = str(tmp_path / "als_cold")
        model.save(path)
        loaded = CompatALSModel.load(path)
        test = {"user": np.array([0, 3]), "item": np.array([0, 0]),
                "rating": np.array([1.0, 1.0], np.float32)}
        out = loaded.transform(test)
        np.testing.assert_array_equal(out["user"], [0])  # drop survived
        assert "p" in out  # predictionCol survived
        # nan mode round-trips too
        m2 = ALS().setRank(3).setMaxIter(2).setImplicitPrefs(True).fit(train)
        m2.save(str(tmp_path / "als_nan"))
        l2 = CompatALSModel.load(str(tmp_path / "als_nan"))
        out2 = l2.transform(test)
        assert np.isfinite(out2["prediction"][0])
        assert np.isnan(out2["prediction"][1])
        np.testing.assert_array_equal(
            l2.transform(test)["prediction"],
            m2.transform(test)["prediction"],
        )

    def test_checkpoint_interval_accepted_noop(self, rng):
        """checkpointInterval is API-parity only: the reference's DAL path
        ignores it too (survey §5)."""
        als = ALS().setCheckpointInterval(5)
        assert als.getCheckpointInterval() == 5
        assert ALS().setCheckpointInterval(-1).getCheckpointInterval() == -1
        with pytest.raises(ValueError):
            ALS().setCheckpointInterval(0)
        df = self._ratings_df(rng)
        model = als.setRank(3).setMaxIter(2).setImplicitPrefs(True).fit(df)
        assert model.userFactors.shape[1] == 3

    def test_prediction_col(self, rng):
        df = self._ratings_df(rng)
        model = (
            ALS().setRank(3).setMaxIter(2).setImplicitPrefs(True)
            .setPredictionCol("score").fit(df)
        )
        out = model.transform(df)
        assert "score" in out and "prediction" not in out


class TestReviewRegressions:
    def test_batch_predict_raises(self, rng):
        df = _df(rng)
        model = KMeans().setK(3).setSeed(1).fit(df)
        with pytest.raises(TypeError):
            model.predict(df["features"][:5])

    def test_weightcol_with_ndarray_raises(self, rng):
        with pytest.raises(ValueError):
            KMeans().setK(2).setWeightCol("w").fit(np.zeros((10, 2)))

    def test_nonnegative_builder(self, rng):
        mask = rng.random((20, 15)) < 0.3
        u, i = np.nonzero(mask)
        df = {"user": u, "item": i,
              "rating": rng.integers(1, 6, len(u)).astype(np.float32)}
        model = ALS().setRank(3).setMaxIter(3).setNonnegative(True).fit(df)
        assert (model.userFactors >= 0).all()

    def test_nonnegative_max_iter_zero_contract(self, rng):
        """nonnegative must hold even at max_iter=0 (abs-projected init)."""
        from oap_mllib_tpu import ALS as CoreALS

        u = np.array([0, 1]); i = np.array([0, 1])
        r = np.array([1.0, 2.0], np.float32)
        m = CoreALS(rank=3, max_iter=0, nonnegative=True).fit(u, i, r)
        assert (m.user_factors_ >= 0).all() and (m.item_factors_ >= 0).all()


class TestEvaluators:
    def _brute_silhouette(self, x, labels, dist):
        n = len(x)
        if dist == "cosine":
            xn = x / np.linalg.norm(x, axis=1, keepdims=True)
            D = 1.0 - xn @ xn.T
        else:
            D = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        scores = []
        for i in range(n):
            own = labels == labels[i]
            if own.sum() < 2:
                scores.append(0.0)
                continue
            a = D[i][own].sum() / (own.sum() - 1)
            b = min(
                D[i][labels == c].mean()
                for c in np.unique(labels) if c != labels[i]
            )
            scores.append((b - a) / max(a, b))
        return float(np.mean(scores))

    @pytest.mark.parametrize("dist", ["squaredEuclidean", "cosine"])
    def test_clustering_evaluator_matches_brute_force(self, rng, dist):
        from oap_mllib_tpu.compat import ClusteringEvaluator

        x = rng.normal(size=(80, 5)) + 2.0
        labels = rng.integers(0, 3, 80)
        df = {"features": x, "prediction": labels}
        ev = ClusteringEvaluator().setDistanceMeasure(dist)
        got = ev.evaluate(df)
        np.testing.assert_allclose(got, self._brute_silhouette(x, labels, dist),
                                   atol=1e-10)
        assert ev.isLargerBetter()

    def test_clustering_evaluator_end_to_end(self, rng):
        from oap_mllib_tpu.compat import ClusteringEvaluator

        proto = rng.normal(size=(3, 4)) * 6
        x = proto[rng.integers(3, size=300)] + 0.05 * rng.normal(size=(300, 4))
        model = KMeans().setK(3).setSeed(1).fit({"features": x})
        sil = ClusteringEvaluator().evaluate(model.transform({"features": x}))
        assert sil > 0.95  # tight, well-separated blobs

    def test_clustering_evaluator_coincident_duplicates(self):
        """a == b == 0 (duplicate points coincident with two cluster means)
        defines s(i) = 0 (Spark/sklearn convention) — must not NaN."""
        import warnings

        from oap_mllib_tpu.compat import ClusteringEvaluator

        # two clusters, each a pair of identical points at the same spot:
        # within-cluster distance a = 0; and put both clusters at the SAME
        # location so the between-cluster distance b = 0 too
        x = np.zeros((4, 3))
        labels = np.array([0, 0, 1, 1])
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # 0/0 would raise RuntimeWarning
            got = ClusteringEvaluator().evaluate(
                {"features": x, "prediction": labels}
            )
        assert np.isfinite(got)
        assert got == 0.0

    def test_clustering_evaluator_validation(self):
        from oap_mllib_tpu.compat import ClusteringEvaluator

        df = {"features": np.zeros((4, 2)), "prediction": np.zeros(4, int)}
        with pytest.raises(ValueError, match="2 clusters"):
            ClusteringEvaluator().evaluate(df)
        with pytest.raises(ValueError, match="distanceMeasure"):
            ClusteringEvaluator().setDistanceMeasure("manhattan").evaluate(df)

    def test_regression_evaluator_metrics(self, rng):
        from oap_mllib_tpu.compat import RegressionEvaluator

        label = rng.normal(size=50)
        pred = label + rng.normal(size=50) * 0.1
        df = {"rating": label, "prediction": pred}
        err = pred - label
        ev = RegressionEvaluator(labelCol="rating")
        np.testing.assert_allclose(
            ev.evaluate(df), np.sqrt(np.mean(err ** 2)))
        np.testing.assert_allclose(
            ev.setMetricName("mse").evaluate(df), np.mean(err ** 2))
        np.testing.assert_allclose(
            ev.setMetricName("mae").evaluate(df), np.mean(np.abs(err)))
        r2 = 1 - np.sum(err ** 2) / np.sum((label - label.mean()) ** 2)
        np.testing.assert_allclose(ev.setMetricName("r2").evaluate(df), r2)
        assert ev.isLargerBetter()
        with pytest.raises(ValueError):
            ev.setMetricName("rmsle").evaluate(df)
