"""Contract tests for the PySpark adapter (compat/pyspark.py).

pyspark is not installable in this environment, so these tests run the
adapter against a mock implementing exactly the duck-typed DataFrame
surface the adapter is written to (select/collect/columns/sparkSession
.createDataFrame) — the same surface a real Spark DataFrame satisfies.
Each test mirrors a reference PySpark example's flow verbatim-minus-
import (examples/als-pyspark/als-pyspark.py, kmeans-pyspark.py,
pca-pyspark.py).
"""

import numpy as np
import pytest

from oap_mllib_tpu.compat.pyspark import (
    ALS,
    ClusteringEvaluator,
    KMeans,
    PCA,
    RegressionEvaluator,
)


class FakeSession:
    def createDataFrame(self, data, schema):
        cols = {name: [row[j] for row in data] for j, name in enumerate(schema)}
        return FakeDataFrame(cols, self)


class FakeDataFrame:
    """The duck-typed surface the adapter touches — nothing more."""

    def __init__(self, columns: dict, session: FakeSession):
        self._cols = columns
        self._session = session

    @property
    def columns(self):
        return list(self._cols)

    @property
    def sparkSession(self):
        return self._session

    def select(self, *names):
        return FakeDataFrame({n: self._cols[n] for n in names}, self._session)

    def collect(self):
        names = list(self._cols)
        n = len(self._cols[names[0]]) if names else 0
        return [tuple(self._cols[c][i] for c in names) for i in range(n)]


class FakeVector:
    """Stands in for pyspark.ml.linalg.DenseVector (toArray duck-type)."""

    def __init__(self, values):
        self._v = np.asarray(values, np.float64)

    def toArray(self):
        return self._v


@pytest.fixture
def session():
    return FakeSession()


def _df(session, **cols):
    n = len(next(iter(cols.values())))
    assert all(len(v) == n for v in cols.values())
    return FakeDataFrame({k: list(v) for k, v in cols.items()}, session)


class TestKMeansAdapter:
    def test_kmeans_example_flow(self, rng, session):
        """kmeans-pyspark.py verbatim-minus-import: fit -> transform ->
        ClusteringEvaluator.evaluate."""
        proto = rng.normal(size=(2, 5)) * 8
        x = proto[rng.integers(2, size=200)] + 0.1 * rng.normal(size=(200, 5))
        dataset = _df(session, features=[list(row) for row in x])

        kmeans = KMeans().setK(2).setSeed(1)
        model = kmeans.fit(dataset)
        predictions = model.transform(dataset)
        assert predictions.columns == ["features", "prediction"]

        evaluator = ClusteringEvaluator()
        silhouette = evaluator.evaluate(predictions)
        assert silhouette > 0.95  # tight separated blobs

        centers = model.clusterCenters()
        assert np.asarray(centers).shape == (2, 5)

    def test_vector_column_duck_typing(self, rng, session):
        """Features as toArray() vectors (the real ml.linalg case)."""
        x = rng.normal(size=(50, 3))
        dataset = _df(session, features=[FakeVector(r) for r in x])
        model = KMeans(k=3, seed=2).fit(dataset)
        out = model.transform(dataset)
        assert len(out.collect()) == 50
        assert model.predict(FakeVector(x[0])) in (0, 1, 2)

    def test_empty_input_transform(self, rng, session):
        """An empty split (randomSplit can produce one) transforms to an
        empty DataFrame with the prediction column — pyspark.ml
        semantics, not a shape crash."""
        x = rng.normal(size=(40, 3))
        dataset = _df(session, features=[list(r) for r in x])
        model = KMeans(k=2, seed=1).fit(dataset)
        empty = _df(session, features=[])
        out = model.transform(empty)
        assert out.collect() == []
        assert out.columns == ["features", "prediction"]
        pca = PCA(k=2, inputCol="features", outputCol="pc").fit(dataset)
        assert pca.transform(empty).collect() == []

    def test_retransform_replaces_prediction_column(self, rng, session):
        """Transforming an already-scored DataFrame must REPLACE the
        prediction column (withColumn semantics), not append a
        duplicate name."""
        x = rng.normal(size=(40, 3))
        dataset = _df(session, features=[list(r) for r in x])
        model = KMeans(k=2, seed=1).fit(dataset)
        once = model.transform(dataset)
        twice = model.transform(once)
        assert twice.columns == ["features", "prediction"]
        assert [r[-1] for r in twice.collect()] == [
            r[-1] for r in once.collect()
        ]
        # withColumn replaces IN PLACE: a reordered frame keeps the
        # prediction column at its original position
        reordered = _df(
            session,
            prediction=[0] * 40,
            features=[list(r) for r in x],
        )
        out = model.transform(reordered)
        assert out.columns == ["prediction", "features"]
        assert [r[0] for r in out.collect()] == [
            r[-1] for r in once.collect()
        ]

    def test_weight_col(self, rng, session):
        x = rng.normal(size=(60, 4))
        w = np.ones(60)
        dataset = _df(
            session, features=[list(r) for r in x], w=list(w)
        )
        model = KMeans(k=2, seed=1, weightCol="w").fit(dataset)
        assert model.summary.accelerated


class TestPipelineAdapter:
    def test_pca_kmeans_pipeline_over_dataframes(self, rng, session):
        """Pipeline is data-plane agnostic: the same class chains the
        DataFrame adapters (PCA features feed K-Means through the
        adapter's transform DataFrames)."""
        from oap_mllib_tpu.compat.pyspark import Pipeline

        proto = rng.normal(size=(3, 6)) * 8
        x = proto[rng.integers(3, size=150)] + 0.1 * rng.normal(size=(150, 6))
        dataset = _df(session, features=[list(r) for r in x])
        pipe = Pipeline(stages=[
            PCA(k=3, inputCol="features", outputCol="pca"),
            KMeans(k=3, seed=1, featuresCol="pca"),
        ])
        model = pipe.fit(dataset)
        out = model.transform(dataset)
        assert out.columns == ["features", "pca", "prediction"]
        assert len(np.unique([r[2] for r in out.collect()])) == 3


class TestPCAAdapter:
    def test_pca_example_flow(self, rng, session):
        """pca-pyspark.py verbatim-minus-import: keyword constructor,
        fit, pc / explainedVariance, transform appends outputCol."""
        x = rng.normal(size=(300, 6)) @ rng.normal(size=(6, 6))
        dataset = _df(session, features=[list(r) for r in x])
        pca = PCA(k=3, inputCol="features", outputCol="pcaFeatures")
        model = pca.fit(dataset)
        assert np.asarray(model.pc).shape == (6, 3)
        assert len(np.asarray(model.explainedVariance)) == 3
        out = model.transform(dataset)
        assert out.columns == ["features", "pcaFeatures"]
        first = out.collect()[0]
        assert len(first[1]) == 3  # projected vector
        # projection parity vs direct NumPy (no centering — Spark parity,
        # models/pca.py transform contract)
        ref = x[0] @ np.asarray(model.pc)
        np.testing.assert_allclose(np.asarray(first[1]), ref, atol=1e-3)


class TestALSAdapter:
    def _ratings_df(self, rng, session, n=1500, nu=40, ni=30):
        u = rng.integers(0, nu, n)
        i = rng.integers(0, ni, n)
        xt = rng.normal(size=(nu, 3))
        yt = rng.normal(size=(ni, 3))
        r = (xt[u] * yt[i]).sum(1) + 0.05 * rng.normal(size=n)
        return (
            _df(
                session,
                userId=[int(v) for v in u],
                movieId=[int(v) for v in i],
                rating=[float(v) for v in r],
            ),
            u, i, r,
        )

    def test_als_example_flow(self, rng, session):
        """als-pyspark.py verbatim-minus-import: keyword constructor
        (userCol/itemCol/ratingCol/coldStartStrategy), getters used by
        the example's print, fit, transform, RegressionEvaluator."""
        training, u, i, r = self._ratings_df(rng, session)
        als = ALS(rank=5, maxIter=5, regParam=0.01,
                  userCol="userId", itemCol="movieId", ratingCol="rating",
                  coldStartStrategy="drop")
        # the example prints every one of these (als-pyspark.py:55-57)
        assert als.getImplicitPrefs() is False
        assert als.getRank() == 5 and als.getMaxIter() == 5
        assert als.getRegParam() == 0.01 and als.getAlpha() == 1.0
        assert als.getSeed() == 0
        model = als.fit(training)

        predictions = model.transform(training)
        assert predictions.columns == [
            "userId", "movieId", "rating", "prediction"
        ]
        evaluator = RegressionEvaluator(metricName="rmse", labelCol="rating",
                                        predictionCol="prediction")
        rmse = evaluator.evaluate(predictions)
        assert rmse < 0.5  # low-rank synthetic data fits well

        assert model.rank == 5
        assert model.userFactors.shape[1] == 5

    def test_cold_start_drop_removes_unseen_rows(self, rng, session):
        training, u, i, r = self._ratings_df(rng, session, nu=20, ni=15)
        als = ALS(rank=3, maxIter=2, userCol="userId", itemCol="movieId",
                  ratingCol="rating", coldStartStrategy="drop")
        model = als.fit(training)
        test = _df(
            session,
            userId=[0, 1, 999],  # 999 unseen
            movieId=[0, 1, 0],
            rating=[1.0, 2.0, 3.0],
        )
        out = model.transform(test)
        rows = out.collect()
        assert len(rows) == 2  # unseen user dropped
        assert all(np.isfinite(row[3]) for row in rows)

    def test_cold_start_nan_keeps_rows(self, rng, session):
        training, *_ = self._ratings_df(rng, session, nu=20, ni=15)
        model = ALS(rank=3, maxIter=2, userCol="userId", itemCol="movieId",
                    ratingCol="rating").fit(training)
        test = _df(session, userId=[0, 999], movieId=[0, 0],
                   rating=[1.0, 2.0])
        rows = model.transform(test).collect()
        assert len(rows) == 2
        assert np.isfinite(rows[0][3]) and np.isnan(rows[1][3])

    def test_cold_start_drop_all_rows(self, rng, session):
        """Every pair cold: transform must return an EMPTY DataFrame, not
        raise (on real Spark the explicitly-typed output schema is what
        makes the empty createDataFrame legal)."""
        training, *_ = self._ratings_df(rng, session, nu=20, ni=15)
        model = ALS(rank=3, maxIter=2, userCol="userId", itemCol="movieId",
                    ratingCol="rating", coldStartStrategy="drop").fit(training)
        test = _df(session, userId=[900, 901], movieId=[0, 1],
                   rating=[1.0, 2.0])
        out = model.transform(test)
        assert out.collect() == []
        assert out.columns == ["userId", "movieId", "rating", "prediction"]

    def test_implicit_mode(self, rng, session):
        training, u, i, r = self._ratings_df(rng, session)
        model = ALS(rank=4, maxIter=3, implicitPrefs=True, alpha=40.0,
                    userCol="userId", itemCol="movieId",
                    ratingCol="rating").fit(training)
        recs = model.recommendForAllUsers(5)
        assert recs.shape == (model.userFactors.shape[0], 5)
