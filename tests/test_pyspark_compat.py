"""Contract tests for the PySpark adapter (compat/pyspark.py).

Dual-plane: every test is parametrized over (a) a mock implementing
exactly the duck-typed DataFrame surface the adapter is written to
(select/collect/columns/sparkSession.createDataFrame) and (b) a REAL
local SparkSession when pyspark is importable — the hosted CI installs
pyspark + a JVM precisely so the real plane executes there (the
reference's CI runs its examples on real Spark, dev/ci-test.sh:60-62);
in pyspark-less environments like this image the real plane skips and
the mock plane still pins the contract.  Each test mirrors a reference
PySpark example's flow verbatim-minus-import
(examples/als-pyspark/als-pyspark.py, kmeans-pyspark.py,
pca-pyspark.py).
"""

import os

import numpy as np
import pytest

from oap_mllib_tpu.compat.pyspark import (
    ALS,
    ClusteringEvaluator,
    KMeans,
    PCA,
    RegressionEvaluator,
)


class FakeSession:
    def createDataFrame(self, data, schema):
        cols = {name: [row[j] for row in data] for j, name in enumerate(schema)}
        return FakeDataFrame(cols, self)


class FakeDataFrame:
    """The duck-typed surface the adapter touches — nothing more."""

    def __init__(self, columns: dict, session: FakeSession):
        self._cols = columns
        self._session = session

    @property
    def columns(self):
        return list(self._cols)

    @property
    def sparkSession(self):
        return self._session

    def select(self, *names):
        return FakeDataFrame({n: self._cols[n] for n in names}, self._session)

    def collect(self):
        names = list(self._cols)
        n = len(self._cols[names[0]]) if names else 0
        return [tuple(self._cols[c][i] for c in names) for i in range(n)]


class FakeVector:
    """Stands in for pyspark.ml.linalg.DenseVector (toArray duck-type)."""

    def __init__(self, values):
        self._v = np.asarray(values, np.float64)

    def toArray(self):
        return self._v


_REAL = {"sess": None, "tried": False}


def _real_spark():
    """Cached local SparkSession, or None when pyspark is absent (one
    JVM for the whole test module; never torn down mid-run)."""
    if not _REAL["tried"]:
        _REAL["tried"] = True
        try:
            from pyspark.sql import SparkSession
        except ImportError:
            return None
        _REAL["sess"] = (
            SparkSession.builder.master("local[2]")
            .appName("oap-mllib-tpu-adapter-tests")
            .config("spark.ui.enabled", "false")
            .config("spark.ui.showConsoleProgress", "false")
            .getOrCreate()
        )
    return _REAL["sess"]


@pytest.fixture(params=["mock", "spark"])
def session(request):
    if request.param == "mock":
        return FakeSession()
    spark = _real_spark()
    if spark is None:
        if os.environ.get("CI") in ("true", "1"):
            # the hosted workflow installs pyspark; a silent skip there
            # would un-prove the drop-in claim (VERDICT r4 missing #1)
            pytest.fail("pyspark is required in CI but not importable")
        pytest.skip("pyspark not installed — real-Spark plane runs in CI")
    return spark


def _dense(session, values):
    """A dense vector cell: ml.linalg on the real plane, the toArray
    duck-type on the mock."""
    if isinstance(session, FakeSession):
        return FakeVector(values)
    from pyspark.ml.linalg import Vectors

    return Vectors.dense([float(v) for v in values])


def _df(session, types=None, **cols):
    """Build a DataFrame on either plane.  ``types`` maps column name ->
    {"double", "bigint", "array<double>"} and is REQUIRED on the real
    plane when a column is empty (Spark cannot infer a schema from an
    empty dataset; the mock never infers)."""
    n = len(next(iter(cols.values())))
    assert all(len(v) == n for v in cols.values())
    if isinstance(session, FakeSession):
        return FakeDataFrame({k: list(v) for k, v in cols.items()}, session)
    names = list(cols)
    rows = [tuple(cols[c][i] for c in names) for i in range(n)]
    if n == 0 or types:
        from pyspark.sql.types import (
            ArrayType,
            DoubleType,
            LongType,
            StructField,
            StructType,
        )

        tmap = {
            "double": DoubleType(),
            "bigint": LongType(),
            "array<double>": ArrayType(DoubleType()),
        }
        fields = [
            StructField(c, tmap[(types or {})[c]], True) for c in names
        ]
        return session.createDataFrame(rows, StructType(fields))
    return session.createDataFrame(rows, names)


class TestKMeansAdapter:
    def test_kmeans_example_flow(self, rng, session):
        """kmeans-pyspark.py verbatim-minus-import: fit -> transform ->
        ClusteringEvaluator.evaluate."""
        proto = rng.normal(size=(2, 5)) * 8
        x = proto[rng.integers(2, size=200)] + 0.1 * rng.normal(size=(200, 5))
        dataset = _df(session, features=[list(row) for row in x])

        kmeans = KMeans().setK(2).setSeed(1)
        model = kmeans.fit(dataset)
        predictions = model.transform(dataset)
        assert predictions.columns == ["features", "prediction"]

        evaluator = ClusteringEvaluator()
        silhouette = evaluator.evaluate(predictions)
        assert silhouette > 0.95  # tight separated blobs

        centers = model.clusterCenters()
        assert np.asarray(centers).shape == (2, 5)

    def test_vector_column_duck_typing(self, rng, session):
        """Features as toArray() vectors (the real ml.linalg case)."""
        x = rng.normal(size=(50, 3))
        dataset = _df(session, features=[_dense(session, r) for r in x])
        model = KMeans(k=3, seed=2).fit(dataset)
        out = model.transform(dataset)
        assert len(out.collect()) == 50
        assert model.predict(FakeVector(x[0])) in (0, 1, 2)

    def test_empty_input_transform(self, rng, session):
        """An empty split (randomSplit can produce one) transforms to an
        empty DataFrame with the prediction column — pyspark.ml
        semantics, not a shape crash."""
        x = rng.normal(size=(40, 3))
        dataset = _df(session, features=[list(r) for r in x])
        model = KMeans(k=2, seed=1).fit(dataset)
        empty = _df(session, types={"features": "array<double>"}, features=[])
        out = model.transform(empty)
        assert out.collect() == []
        assert out.columns == ["features", "prediction"]
        pca = PCA(k=2, inputCol="features", outputCol="pc").fit(dataset)
        assert pca.transform(empty).collect() == []

    def test_retransform_replaces_prediction_column(self, rng, session):
        """Transforming an already-scored DataFrame must REPLACE the
        prediction column (withColumn semantics), not append a
        duplicate name."""
        x = rng.normal(size=(40, 3))
        dataset = _df(session, features=[list(r) for r in x])
        model = KMeans(k=2, seed=1).fit(dataset)
        once = model.transform(dataset)
        twice = model.transform(once)
        assert twice.columns == ["features", "prediction"]
        assert [r[-1] for r in twice.collect()] == [
            r[-1] for r in once.collect()
        ]
        # withColumn replaces IN PLACE: a reordered frame keeps the
        # prediction column at its original position
        reordered = _df(
            session,
            prediction=[0] * 40,
            features=[list(r) for r in x],
        )
        out = model.transform(reordered)
        assert out.columns == ["prediction", "features"]
        assert [r[0] for r in out.collect()] == [
            r[-1] for r in once.collect()
        ]

    def test_weight_col(self, rng, session):
        x = rng.normal(size=(60, 4))
        w = np.ones(60)
        dataset = _df(
            session, features=[list(r) for r in x], w=list(w)
        )
        model = KMeans(k=2, seed=1, weightCol="w").fit(dataset)
        assert model.summary.accelerated


class FakePartitionedDataFrame(FakeDataFrame):
    """FakeDataFrame + the rdd.mapPartitionsWithIndex surface the
    multi-process ingestion uses; records which partitions the filter
    KEPT (returned rows from)."""

    def __init__(self, columns, session, n_parts, kept=None):
        super().__init__(columns, session)
        self._nparts = n_parts
        self.kept = kept if kept is not None else []

    def select(self, *names):
        return FakePartitionedDataFrame(
            {n: self._cols[n] for n in names}, self._session,
            self._nparts, self.kept,
        )

    @property
    def rdd(self):
        rows = self.collect()
        parts = np.array_split(np.arange(len(rows)), self._nparts)
        kept = self.kept

        class _Res:
            def __init__(self, out):
                self._out = out

            def collect(self):
                return self._out

        class _RDD:
            def mapPartitionsWithIndex(self, f):
                out = []
                for pid, idx in enumerate(parts):
                    got = list(f(pid, iter([rows[j] for j in idx])))
                    if got:
                        kept.append(pid)
                    out.extend(got)
                return _Res(out)

        return _RDD()


class TestPartitionedIngestion:
    """The multi-process ingestion helper in isolation: process r must
    keep exactly partitions p % world == r, in partition order."""

    def test_keeps_only_local_partitions(self, session):
        if not isinstance(session, FakeSession):
            pytest.skip("partition-filter accounting is mock-only")
        from oap_mllib_tpu.compat.pyspark import _collect_local_partitions

        df = FakePartitionedDataFrame(
            {"v": list(range(100)), "w": list(range(100, 200))},
            session, n_parts=5,
        )
        rows, cols = _collect_local_partitions(df.select("v"), rank=1,
                                               world=2)
        assert df.kept == [1, 3]  # pid % 2 == 1 only
        assert cols == ["v"]
        assert [r[0] for r in rows] == list(range(20, 40)) + list(range(60, 80))

    def test_union_over_ranks_covers_all_rows_once(self, session):
        if not isinstance(session, FakeSession):
            pytest.skip("partition-filter accounting is mock-only")
        from oap_mllib_tpu.compat.pyspark import _collect_local_partitions

        got = []
        for rank in range(3):
            df = FakePartitionedDataFrame(
                {"v": list(range(50))}, session, n_parts=7
            )
            rows, _ = _collect_local_partitions(df, rank=rank, world=3)
            got.extend(r[0] for r in rows)
        assert sorted(got) == list(range(50))

    def test_zero_partition_rank_raises(self, session):
        """Fewer partitions than world: the starved rank must get a
        clear repartition error, not a shape crash (in a real world the
        check is an allgather so every rank raises together)."""
        if not isinstance(session, FakeSession):
            pytest.skip("partition-filter accounting is mock-only")
        from oap_mllib_tpu.compat.pyspark import _collect_local_partitions

        df = FakePartitionedDataFrame(
            {"v": list(range(10))}, session, n_parts=2
        )
        with pytest.raises(ValueError, match="zero partitions"):
            _collect_local_partitions(df, rank=2, world=3)

    def test_no_rdd_surface_raises(self, session):
        if not isinstance(session, FakeSession):
            pytest.skip("surface-check is mock-only")
        from oap_mllib_tpu.compat.pyspark import _collect_local_partitions

        df = _df(session, v=[1, 2, 3])
        with pytest.raises(TypeError, match="mapPartitionsWithIndex"):
            _collect_local_partitions(df, rank=0, world=2)


class TestAdapterFuzz:
    """Randomized-schema fuzz: for every draw the DataFrame plane must
    produce exactly the dict plane's numbers on the same data — shuffled
    column orders, bystander columns, nan/drop cold-start, and a
    re-transform cycle.  Runs against the mock always and against a real
    SparkSession in CI (the dual-plane ``session`` fixture)."""

    def test_als_matches_dict_plane_fuzz(self, rng, session):
        from oap_mllib_tpu.compat import spark as dictplane

        for trial in range(4):
            nu = int(rng.integers(8, 30))
            ni = int(rng.integers(6, 24))
            nnz = int(rng.integers(60, 300))
            u = rng.integers(0, nu, nnz)
            i = rng.integers(0, ni, nnz)
            r = (rng.random(nnz) * 4 + 1).astype(np.float32)
            strategy = ["nan", "drop"][trial % 2]

            cols = {
                "userId": [int(v) for v in u],
                "movieId": [int(v) for v in i],
                "rating": [float(v) for v in r],
                "bystander": [float(v) for v in rng.random(nnz)],
            }
            names = list(cols)
            rng.shuffle(names)  # random column order
            df = _df(session, **{n: cols[n] for n in names})

            kw = dict(rank=3, maxIter=2, regParam=0.1, seed=trial,
                      userCol="userId", itemCol="movieId",
                      ratingCol="rating", coldStartStrategy=strategy)
            model = ALS(**kw).fit(df)
            oracle = (
                dictplane.ALS().setRank(3).setMaxIter(2).setRegParam(0.1)
                .setSeed(trial).setUserCol("userId").setItemCol("movieId")
                .setRatingCol("rating").setColdStartStrategy(strategy)
                .fit({k: np.asarray(v) for k, v in cols.items()})
            )

            # probe includes unseen ids so both strategies do real work
            pu = np.concatenate([u[:10], [nu + 5]])
            pi = np.concatenate([i[:10], [0]])
            probe_cols = {
                "userId": [int(v) for v in pu],
                "movieId": [int(v) for v in pi],
                "rating": [1.0] * len(pu),
            }
            probe = _df(session, **probe_cols)
            out_rows = model.transform(probe).collect()
            want = oracle.transform(
                {k: np.asarray(v) for k, v in probe_cols.items()}
            )
            got = np.asarray([row[-1] for row in out_rows], np.float64)
            np.testing.assert_allclose(
                got, np.asarray(want["prediction"], np.float64),
                atol=1e-5, rtol=1e-5,
                err_msg=f"trial {trial} strategy={strategy} order={names}",
            )
            if strategy == "drop":
                # the unseen probe user must actually be dropped
                assert len(out_rows) == len(pu) - 1

    def test_kmeans_matches_dict_plane_fuzz(self, rng, session):
        from oap_mllib_tpu.compat import spark as dictplane

        for trial in range(3):
            n = int(rng.integers(40, 120))
            d = int(rng.integers(3, 8))
            k = int(rng.integers(2, 5))
            x = rng.normal(size=(n, d))
            cols = {
                "noise": [float(v) for v in rng.random(n)],
                "features": [list(row) for row in x],
            }
            df = _df(session, **cols)
            model = KMeans(k=k, seed=trial, maxIter=5).fit(df)
            oracle = (
                dictplane.KMeans().setK(k).setSeed(trial).setMaxIter(5)
                .fit({"features": x})
            )
            got = [row[-1] for row in model.transform(df).collect()]
            want = oracle.transform({"features": x})["prediction"]
            np.testing.assert_array_equal(
                got, want, err_msg=f"trial {trial} n={n} d={d} k={k}"
            )
            # a second transform over the scored frame must be stable
            again = [
                row[-1] for row in model.transform(model.transform(df))
                .collect()
            ]
            np.testing.assert_array_equal(again, want)


class TestPipelineAdapter:
    def test_pca_kmeans_pipeline_over_dataframes(self, rng, session):
        """Pipeline is data-plane agnostic: the same class chains the
        DataFrame adapters (PCA features feed K-Means through the
        adapter's transform DataFrames)."""
        from oap_mllib_tpu.compat.pyspark import Pipeline

        proto = rng.normal(size=(3, 6)) * 8
        x = proto[rng.integers(3, size=150)] + 0.1 * rng.normal(size=(150, 6))
        dataset = _df(session, features=[list(r) for r in x])
        pipe = Pipeline(stages=[
            PCA(k=3, inputCol="features", outputCol="pca"),
            KMeans(k=3, seed=1, featuresCol="pca"),
        ])
        model = pipe.fit(dataset)
        out = model.transform(dataset)
        assert out.columns == ["features", "pca", "prediction"]
        assert len(np.unique([r[2] for r in out.collect()])) == 3


class TestPCAAdapter:
    def test_pca_example_flow(self, rng, session):
        """pca-pyspark.py verbatim-minus-import: keyword constructor,
        fit, pc / explainedVariance, transform appends outputCol."""
        x = rng.normal(size=(300, 6)) @ rng.normal(size=(6, 6))
        dataset = _df(session, features=[list(r) for r in x])
        pca = PCA(k=3, inputCol="features", outputCol="pcaFeatures")
        model = pca.fit(dataset)
        assert np.asarray(model.pc).shape == (6, 3)
        assert len(np.asarray(model.explainedVariance)) == 3
        out = model.transform(dataset)
        assert out.columns == ["features", "pcaFeatures"]
        first = out.collect()[0]
        assert len(first[1]) == 3  # projected vector
        # projection parity vs direct NumPy (no centering — Spark parity,
        # models/pca.py transform contract)
        ref = x[0] @ np.asarray(model.pc)
        np.testing.assert_allclose(np.asarray(first[1]), ref, atol=1e-3)


class TestALSAdapter:
    def _ratings_df(self, rng, session, n=1500, nu=40, ni=30):
        u = rng.integers(0, nu, n)
        i = rng.integers(0, ni, n)
        xt = rng.normal(size=(nu, 3))
        yt = rng.normal(size=(ni, 3))
        r = (xt[u] * yt[i]).sum(1) + 0.05 * rng.normal(size=n)
        return (
            _df(
                session,
                userId=[int(v) for v in u],
                movieId=[int(v) for v in i],
                rating=[float(v) for v in r],
            ),
            u, i, r,
        )

    def test_als_example_flow(self, rng, session):
        """als-pyspark.py verbatim-minus-import: keyword constructor
        (userCol/itemCol/ratingCol/coldStartStrategy), getters used by
        the example's print, fit, transform, RegressionEvaluator."""
        training, u, i, r = self._ratings_df(rng, session)
        als = ALS(rank=5, maxIter=5, regParam=0.01,
                  userCol="userId", itemCol="movieId", ratingCol="rating",
                  coldStartStrategy="drop")
        # the example prints every one of these (als-pyspark.py:55-57)
        assert als.getImplicitPrefs() is False
        assert als.getRank() == 5 and als.getMaxIter() == 5
        assert als.getRegParam() == 0.01 and als.getAlpha() == 1.0
        assert als.getSeed() == 0
        model = als.fit(training)

        predictions = model.transform(training)
        assert predictions.columns == [
            "userId", "movieId", "rating", "prediction"
        ]
        evaluator = RegressionEvaluator(metricName="rmse", labelCol="rating",
                                        predictionCol="prediction")
        rmse = evaluator.evaluate(predictions)
        assert rmse < 0.5  # low-rank synthetic data fits well

        assert model.rank == 5
        assert model.userFactors.shape[1] == 5

    def test_cold_start_drop_removes_unseen_rows(self, rng, session):
        training, u, i, r = self._ratings_df(rng, session, nu=20, ni=15)
        als = ALS(rank=3, maxIter=2, userCol="userId", itemCol="movieId",
                  ratingCol="rating", coldStartStrategy="drop")
        model = als.fit(training)
        test = _df(
            session,
            userId=[0, 1, 999],  # 999 unseen
            movieId=[0, 1, 0],
            rating=[1.0, 2.0, 3.0],
        )
        out = model.transform(test)
        rows = out.collect()
        assert len(rows) == 2  # unseen user dropped
        assert all(np.isfinite(row[3]) for row in rows)

    def test_cold_start_nan_keeps_rows(self, rng, session):
        training, *_ = self._ratings_df(rng, session, nu=20, ni=15)
        model = ALS(rank=3, maxIter=2, userCol="userId", itemCol="movieId",
                    ratingCol="rating").fit(training)
        test = _df(session, userId=[0, 999], movieId=[0, 0],
                   rating=[1.0, 2.0])
        rows = model.transform(test).collect()
        assert len(rows) == 2
        assert np.isfinite(rows[0][3]) and np.isnan(rows[1][3])

    def test_cold_start_drop_all_rows(self, rng, session):
        """Every pair cold: transform must return an EMPTY DataFrame, not
        raise (on real Spark the explicitly-typed output schema is what
        makes the empty createDataFrame legal)."""
        training, *_ = self._ratings_df(rng, session, nu=20, ni=15)
        model = ALS(rank=3, maxIter=2, userCol="userId", itemCol="movieId",
                    ratingCol="rating", coldStartStrategy="drop").fit(training)
        test = _df(session, userId=[900, 901], movieId=[0, 1],
                   rating=[1.0, 2.0])
        out = model.transform(test)
        assert out.collect() == []
        assert out.columns == ["userId", "movieId", "rating", "prediction"]

    def test_cross_validator_over_dataframes(self, rng, session):
        """The common pyspark tuning flow is drop-in too: CrossValidator
        accepts a Spark DataFrame (one collect, splits on the dict
        plane) and refits the winner on the ORIGINAL frame so bestModel
        transforms DataFrames."""
        from oap_mllib_tpu.compat.pipeline import (
            CrossValidator,
            ParamGridBuilder,
        )

        training, *_ = self._ratings_df(rng, session)
        cv = CrossValidator(
            estimator=ALS(rank=3, maxIter=3, userCol="userId",
                          itemCol="movieId", ratingCol="rating",
                          coldStartStrategy="drop"),
            estimatorParamMaps=(ParamGridBuilder()
                                .addGrid("regParam", [0.05, 50.0])
                                .build()),
            evaluator=RegressionEvaluator(metricName="rmse",
                                          labelCol="rating"),
            numFolds=2, seed=1,
        )
        model = cv.fit(training)
        assert model.bestParams == {"regParam": 0.05}
        assert model.avgMetrics[0] < model.avgMetrics[1]
        out = model.transform(training)  # DataFrame in, DataFrame out
        assert "prediction" in out.columns
        preds = [r[-1] for r in out.collect()]
        assert np.isfinite(preds).all()

    def test_cv_model_roundtrip_both_planes(self, rng, session, tmp_path):
        """A CV model fit on a DataFrame saves/loads and then transforms
        BOTH planes: a DataFrame (adapter egress) and a dict (the loaded
        wrapper must pass dicts through to its dict-plane inner model) —
        cold-start drop honored on each."""
        from oap_mllib_tpu.compat.pipeline import (
            CrossValidator,
            CrossValidatorModel,
            ParamGridBuilder,
        )

        training, *_ = self._ratings_df(rng, session, nu=20, ni=15)
        model = CrossValidator(
            estimator=ALS(rank=3, maxIter=2, userCol="userId",
                          itemCol="movieId", ratingCol="rating",
                          coldStartStrategy="drop"),
            estimatorParamMaps=(ParamGridBuilder()
                                .addGrid("regParam", [0.05, 5.0]).build()),
            evaluator=RegressionEvaluator(metricName="rmse",
                                          labelCol="rating"),
            numFolds=2, seed=1,
        ).fit(training)
        model.save(str(tmp_path / "cv"))
        loaded = CrossValidatorModel.load(str(tmp_path / "cv"))
        assert loaded.bestParams == model.bestParams
        probe_df = _df(session, userId=[0, 999], movieId=[0, 1],
                       rating=[1.0, 2.0])
        rows = loaded.transform(probe_df).collect()
        assert len(rows) == 1 and np.isfinite(rows[0][-1])
        probe = {"userId": np.array([0, 999]), "movieId": np.array([0, 1]),
                 "rating": np.array([1.0, 2.0], np.float32)}
        out = loaded.transform(probe)
        assert len(out["prediction"]) == 1
        assert np.isfinite(out["prediction"]).all()

    def test_train_validation_split_over_dataframes(self, rng, session):
        from oap_mllib_tpu.compat.pipeline import (
            ParamGridBuilder,
            TrainValidationSplit,
        )

        training, *_ = self._ratings_df(rng, session)
        model = TrainValidationSplit(
            estimator=ALS(rank=3, maxIter=3, userCol="userId",
                          itemCol="movieId", ratingCol="rating",
                          coldStartStrategy="drop"),
            estimatorParamMaps=(ParamGridBuilder()
                                .addGrid("regParam", [0.05, 50.0])
                                .build()),
            evaluator=RegressionEvaluator(metricName="rmse",
                                          labelCol="rating"),
            trainRatio=0.8, seed=1,
        ).fit(training)
        assert model.bestParams == {"regParam": 0.05}
        assert "prediction" in model.transform(training).columns

    def test_recommend_subset_from_dataframe(self, rng, session):
        """recommendForUserSubset takes a DataFrame carrying the id
        column (the pyspark.ml signature); distinct-and-join semantics
        ride the dict plane."""
        training, *_ = self._ratings_df(rng, session, nu=20, ni=15)
        model = ALS(rank=3, maxIter=2, implicitPrefs=True,
                    userCol="userId", itemCol="movieId",
                    ratingCol="rating").fit(training)
        sub = _df(session, userId=[3, 0, 3, 999])
        ids, recs = model.recommendForUserSubset(sub, 4)
        assert list(ids) == [0, 3]
        assert recs.shape == (2, 4)
        assert recs.max() < model.itemFactors.shape[0]

    def test_implicit_mode(self, rng, session):
        training, u, i, r = self._ratings_df(rng, session)
        model = ALS(rank=4, maxIter=3, implicitPrefs=True, alpha=40.0,
                    userCol="userId", itemCol="movieId",
                    ratingCol="rating").fit(training)
        recs = model.recommendForAllUsers(5)
        assert recs.shape == (model.userFactors.shape[0], 5)
