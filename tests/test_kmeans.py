"""K-Means parity + behavior tests.

Modeled on the reference's IntelKMeansSuite (forked Spark estimator suite:
default params, param validation, fit/transform/summary, persistence) plus
the survey §4 takeaway: oracle-parity with absTol against independent
NumPy math, and cost-based (not center-exact) comparison for RNG-sensitive
init (survey §7.3).
"""

import numpy as np
import pytest

from oap_mllib_tpu import KMeans, KMeansModel
from oap_mllib_tpu.config import set_config


def _blobs(rng, n=600, d=8, k=4, spread=0.05):
    """Well-separated gaussian blobs with known centers."""
    centers = rng.normal(size=(k, d)) * 5.0
    assign = rng.integers(k, size=n)
    x = centers[assign] + rng.normal(size=(n, d)) * spread
    return x, centers, assign


def _oracle_lloyd(x, centers, max_iter=50, tol=1e-6):
    """Independent plain-NumPy Lloyd oracle (test-local, not framework code)."""
    c = centers.copy()
    for _ in range(max_iter):
        d2 = ((x[:, None, :] - c[None, :, :]) ** 2).sum(-1)
        a = d2.argmin(1)
        newc = np.stack(
            [x[a == j].mean(0) if np.any(a == j) else c[j] for j in range(len(c))]
        )
        if ((newc - c) ** 2).sum(1).max() <= tol * tol:
            c = newc
            break
        c = newc
    d2 = ((x[:, None, :] - c[None, :, :]) ** 2).sum(-1)
    return c, float(d2.min(1).sum())


class TestDefaults:
    def test_default_params(self):
        km = KMeans()
        assert km.k == 2
        assert km.max_iter == 20
        assert km.tol == 1e-4
        assert km.init_mode == "k-means||"
        assert km.distance_measure == "euclidean"

    def test_param_validation(self):
        with pytest.raises(ValueError):
            KMeans(k=0)
        with pytest.raises(ValueError):
            KMeans(max_iter=-1)
        with pytest.raises(ValueError):
            KMeans(init_mode="bogus")
        with pytest.raises(ValueError):
            KMeans(distance_measure="manhattan")
        with pytest.raises(ValueError):
            KMeans(init_steps=0)


class TestParity:
    def test_cost_matches_oracle_fixed_init(self, rng):
        """Same init => same converged centers/cost as the NumPy oracle."""
        x, true_centers, _ = _blobs(rng)
        k = 4
        init = x[rng.choice(len(x), k, replace=False)]

        import jax.numpy as jnp

        from oap_mllib_tpu.ops.kmeans_ops import lloyd_run

        xj = jnp.asarray(x, jnp.float32)
        w = jnp.ones((len(x),), jnp.float32)
        centers, n_iter, cost, _ = lloyd_run(
            xj, w, jnp.asarray(init, jnp.float32), 50, jnp.asarray(1e-6, jnp.float32)
        )
        oc, ocost = _oracle_lloyd(x, init)
        # sort both center sets for comparison
        order = np.lexsort(np.asarray(centers).T)
        oorder = np.lexsort(oc.T)
        np.testing.assert_allclose(
            np.asarray(centers)[order], oc[oorder], atol=1e-3, rtol=1e-3
        )
        assert abs(float(cost) - ocost) / max(ocost, 1e-9) < 1e-3

    def test_recovers_blob_centers(self, rng):
        x, true_centers, _ = _blobs(rng, n=2000, k=4)
        model = KMeans(k=4, max_iter=50, tol=1e-6, seed=7).fit(x)
        # every true center should be close to some learned center
        d = np.linalg.norm(
            true_centers[:, None, :] - model.cluster_centers_[None, :, :], axis=-1
        )
        assert d.min(axis=1).max() < 0.1

    def test_accelerated_vs_fallback_cost_parity(self, rng):
        """TPU path and fallback path converge to comparable cost."""
        x, _, _ = _blobs(rng, n=1000, k=3)
        m_acc = KMeans(k=3, max_iter=50, tol=1e-6, seed=3).fit(x)
        assert m_acc.summary.accelerated
        set_config(device="cpu")
        m_fb = KMeans(k=3, max_iter=50, tol=1e-6, seed=3).fit(x)
        assert not m_fb.summary.accelerated
        a, b = m_acc.summary.training_cost, m_fb.summary.training_cost
        assert abs(a - b) / max(b, 1e-9) < 0.05


class TestBehavior:
    def test_fit_predict_shapes(self, rng):
        x, _, _ = _blobs(rng)
        model = KMeans(k=4, seed=1).fit(x)
        assert model.cluster_centers_.shape == (4, x.shape[1])
        pred = model.predict(x)
        assert pred.shape == (len(x),)
        assert pred.min() >= 0 and pred.max() < 4

    def test_summary(self, rng):
        x, _, _ = _blobs(rng)
        model = KMeans(k=4, max_iter=30, seed=1).fit(x)
        s = model.summary
        assert s.num_iter >= 1 and s.num_iter <= 30
        assert s.training_cost >= 0
        assert s.timings.total() > 0

    def test_predict_consistent_with_centers(self, rng):
        x, _, _ = _blobs(rng)
        model = KMeans(k=4, seed=1).fit(x)
        d2 = ((x[:, None, :] - model.cluster_centers_[None, :, :]) ** 2).sum(-1)
        np.testing.assert_array_equal(model.predict(x), d2.argmin(1))

    def test_k_equals_one(self, rng):
        x, _, _ = _blobs(rng, k=2)
        model = KMeans(k=1, max_iter=10, seed=0).fit(x)
        np.testing.assert_allclose(
            model.cluster_centers_[0], x.mean(0), atol=1e-3, rtol=1e-3
        )

    def test_max_iter_zero_returns_init(self, rng):
        x, _, _ = _blobs(rng)
        model = KMeans(k=3, max_iter=0, init_mode="random", seed=5).fit(x)
        assert model.cluster_centers_.shape == (3, x.shape[1])

    def test_random_init_mode(self, rng):
        x, _, _ = _blobs(rng)
        model = KMeans(k=4, init_mode="random", seed=2, max_iter=50, tol=1e-6).fit(x)
        rand_cost = KMeans(
            k=4, max_iter=0, init_mode="random", seed=2
        ).fit(x).summary.training_cost
        assert model.summary.training_cost < rand_cost + 1e-6

    def test_weighted_fit(self, rng):
        """Row weights shift the k=1 center to the weighted mean."""
        x = np.array([[0.0, 0.0], [10.0, 10.0]])
        w = np.array([3.0, 1.0])
        model = KMeans(k=1, max_iter=5, seed=0).fit(x, sample_weight=w)
        np.testing.assert_allclose(model.cluster_centers_[0], [2.5, 2.5], atol=1e-4)

    def test_cosine_falls_back(self, rng):
        x, _, _ = _blobs(rng)
        x = np.abs(x) + 0.1
        model = KMeans(k=3, distance_measure="cosine", seed=1).fit(x)
        assert not model.summary.accelerated
        assert model.cluster_centers_.shape == (3, x.shape[1])

    def test_non2d_raises(self):
        with pytest.raises(ValueError):
            KMeans(k=2).fit(np.zeros((5,)))


class TestPersistence:
    def test_save_load_roundtrip(self, tmp_path, rng):
        x, _, _ = _blobs(rng)
        model = KMeans(k=4, seed=1).fit(x)
        p = str(tmp_path / "kmeans_model")
        model.save(p)
        loaded = KMeansModel.load(p)
        np.testing.assert_array_equal(loaded.cluster_centers_, model.cluster_centers_)
        assert loaded.distance_measure == model.distance_measure
        np.testing.assert_array_equal(loaded.predict(x), model.predict(x))


class TestSharding:
    def test_uneven_rows_padding(self, rng):
        """Row counts not divisible by 8 devices are padded and masked out."""
        for n in (7, 8, 9, 123):
            x = rng.normal(size=(n, 4))
            model = KMeans(k=2, max_iter=20, seed=0, init_mode="random").fit(x)
            # cost must equal direct recomputation on unpadded data
            d2 = ((x[:, None, :] - model.cluster_centers_[None, :, :]) ** 2).sum(-1)
            direct = d2.min(1).sum()
            assert abs(model.summary.training_cost - direct) / max(direct, 1e-9) < 1e-4


class TestChunkedScoring:
    def test_predict_and_cost_chunked_exact(self, rng, monkeypatch):
        """Row-chunked predict/compute_cost (incl. a ragged tail) match the
        unchunked results exactly."""
        x, _, _ = _blobs(rng, n=257, d=6, k=3)
        model = KMeans(k=3, max_iter=10, seed=0, init_mode="random").fit(x)
        full_pred = model.predict(x)
        full_cost = model.compute_cost(x)
        # budget of 300 elems at k=3, d=6 -> 33-row chunks (+ ragged tail)
        monkeypatch.setattr(KMeansModel, "_PREDICT_BUDGET", 300)
        np.testing.assert_array_equal(model.predict(x), full_pred)
        np.testing.assert_allclose(model.compute_cost(x), full_cost, rtol=1e-6)


class TestModelParallel:
    """Mesh-sharded linalg for K-Means: centroids feature-sharded over the
    MODEL axis of a (data=4, model=2) mesh (survey §5 scope; the shard_map
    program in kmeans_ops.lloyd_run_model_sharded)."""

    def test_2d_mesh_matches_1d(self, rng):
        x, _, _ = _blobs(rng, n=512, d=8, k=4)
        m1 = KMeans(k=4, max_iter=25, seed=3, init_mode="random").fit(x)
        set_config(model_parallel=2)
        m2 = KMeans(k=4, max_iter=25, seed=3, init_mode="random").fit(x)
        # same host-side RNG -> same init -> identical Lloyd trajectory
        assert m1.summary.num_iter == m2.summary.num_iter
        np.testing.assert_allclose(
            m1.cluster_centers_, m2.cluster_centers_, atol=1e-5
        )
        # cost tolerance is loose: the f32 distance identity |x|^2+|c|^2-2xc
        # cancels ~4 decades on tight blobs (|x|^2 ~ 200 vs min-dist ~ 0.02),
        # and the model-sharded path sums feature-block partials in a
        # different order — centers are exact, the summed objective wobbles
        np.testing.assert_allclose(
            m1.summary.training_cost, m2.summary.training_cost, rtol=5e-3
        )
        np.testing.assert_allclose(
            m1.summary.cluster_sizes, m2.summary.cluster_sizes, atol=1e-6
        )

    def test_2d_mesh_feature_padding(self, rng):
        """d=7 does not divide model=2: zero-padded feature columns must
        not perturb centers, cost, or the returned center shape."""
        x, _, _ = _blobs(rng, n=300, d=7, k=3)
        set_config(model_parallel=2)
        model = KMeans(k=3, max_iter=30, seed=1, init_mode="random").fit(x)
        assert model.cluster_centers_.shape == (3, 7)
        ref_c, ref_cost = _oracle_lloyd(
            x, model.cluster_centers_.copy(), max_iter=1, tol=1e30
        )
        # a converged fit is a Lloyd fixed point: one more oracle step
        # cannot move the centers
        np.testing.assert_allclose(model.cluster_centers_, ref_c, atol=1e-4)
        d2 = ((x[:, None, :] - model.cluster_centers_[None, :, :]) ** 2).sum(-1)
        assert abs(model.summary.training_cost - d2.min(1).sum()) < 1e-4 * max(
            d2.min(1).sum(), 1.0
        )

    def test_2d_mesh_matches_oracle(self, rng):
        x, true_c, _ = _blobs(rng, n=640, d=8, k=4, spread=0.02)
        set_config(model_parallel=2)
        model = KMeans(k=4, max_iter=40, seed=0).fit(x)
        # well-separated blobs: recovered centers match the generators
        got = model.cluster_centers_
        for c in true_c:
            assert np.min(np.sum((got - c) ** 2, axis=1)) < 0.01

    def test_forced_xla_honored_on_model_mesh(self, rng):
        """kmeans_kernel="xla" must force the GSPMD data-parallel Lloyd
        even when model_parallel > 1 (the A/B knob), and agree with the
        model-sharded program."""
        from oap_mllib_tpu.utils import progcache

        def sharded_builds():
            # model-sharded Lloyd programs built so far (the registry
            # replaced the old functools.lru_cache here)
            return (
                progcache.stats()["by_algo"]
                .get("kmeans.lloyd_model_sharded", {})
                .get("misses", 0)
            )

        x, _, _ = _blobs(rng, n=256, d=8, k=3)
        set_config(model_parallel=2, kmeans_kernel="xla")
        before = sharded_builds()
        m1 = KMeans(k=3, max_iter=20, seed=4, init_mode="random").fit(x)
        assert sharded_builds() == before
        set_config(kmeans_kernel="auto")
        m2 = KMeans(k=3, max_iter=20, seed=4, init_mode="random").fit(x)
        np.testing.assert_allclose(
            m1.cluster_centers_, m2.cluster_centers_, atol=1e-5
        )

    def test_invalid_kernel_raises_on_model_sharded_route(self, rng):
        """kmeans_kernel validation must run even when the model axis
        routes the fit away from the pallas/xla dispatch."""
        x, _, _ = _blobs(rng, n=64, d=8, k=2)
        set_config(model_parallel=2, kmeans_kernel="typo")
        with pytest.raises(ValueError, match="kmeans_kernel"):
            KMeans(k=2, max_iter=2, init_mode="random").fit(x)

    def test_weighted_2d_mesh(self, rng):
        """Row weights thread through the model-sharded path unchanged."""
        x, _, _ = _blobs(rng, n=256, d=8, k=3)
        w = (rng.random(256) + 0.5).astype(np.float64)
        m1 = KMeans(k=3, max_iter=20, seed=5, init_mode="random").fit(
            x, sample_weight=w
        )
        set_config(model_parallel=2)
        m2 = KMeans(k=3, max_iter=20, seed=5, init_mode="random").fit(
            x, sample_weight=w
        )
        np.testing.assert_allclose(
            m1.cluster_centers_, m2.cluster_centers_, atol=1e-5
        )
        np.testing.assert_allclose(
            m1.summary.cluster_sizes, m2.summary.cluster_sizes, atol=1e-5
        )


class TestRegressions:
    def test_cosine_compute_cost_consistent_with_training(self, rng):
        """compute_cost must use the model's distance measure (cosine models
        previously got a squared-euclidean cost)."""
        x = np.abs(rng.normal(size=(60, 5))) + 0.1
        m = KMeans(k=3, distance_measure="cosine", seed=1, max_iter=30, tol=1e-6).fit(x)
        # recomputed cost on training data should match training cost closely
        tc = m.summary.training_cost
        assert abs(m.compute_cost(x) - tc) < 1e-6 + 0.05 * tc
        # and must be on the cosine scale (bounded by n since 1-cos <= 2)
        assert m.compute_cost(x) < 2 * len(x)

    def test_chunked_accumulate_matches_unchunked(self, rng):
        """row_chunks>1 (the bench kernel path) must match the unchunked
        accumulate bit-for-bit-ish on identical inputs."""
        import jax.numpy as jnp
        from oap_mllib_tpu.ops.kmeans_ops import lloyd_run

        x, _, _ = _blobs(rng, n=640, d=8, k=4)
        init = x[rng.choice(len(x), 4, replace=False)]
        xj = jnp.asarray(x, jnp.float32)
        w = jnp.ones((len(x),), jnp.float32)
        cj = jnp.asarray(init, jnp.float32)
        tol = jnp.asarray(1e-6, jnp.float32)
        c1, i1, cost1, _ = lloyd_run(xj, w, cj, 20, tol)
        c2, i2, cost2, _ = lloyd_run(xj, w, cj, 20, tol, 8)
        assert int(i1) == int(i2)
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), atol=1e-4, rtol=1e-5)
        # f32 cost sums reassociate across chunk boundaries -> ~1e-4 rel drift
        np.testing.assert_allclose(float(cost1), float(cost2), rtol=1e-3)

    def test_chunked_pads_indivisible_rows(self, rng):
        """Rows that don't divide row_chunks pad with weight-0 rows inside
        lloyd_run (they used to raise) — the budget stays enforceable for
        ANY n and results match the unchunked loop."""
        import jax.numpy as jnp
        from oap_mllib_tpu.ops.kmeans_ops import lloyd_run

        x, _, _ = _blobs(rng, n=101, d=5, k=3)
        init = x[rng.choice(len(x), 3, replace=False)]
        xj = jnp.asarray(x, jnp.float32)
        w = jnp.ones((len(x),), jnp.float32)
        cj = jnp.asarray(init, jnp.float32)
        tol = jnp.asarray(1e-6, jnp.float32)
        c1, i1, cost1, n1 = lloyd_run(xj, w, cj, 15, tol)
        c2, i2, cost2, n2 = lloyd_run(xj, w, cj, 15, tol, 4)  # 101 % 4 != 0
        assert int(i1) == int(i2)
        np.testing.assert_allclose(
            np.asarray(c1), np.asarray(c2), atol=1e-5, rtol=1e-5
        )
        np.testing.assert_allclose(float(cost1), float(cost2), rtol=1e-3)
        np.testing.assert_allclose(
            np.asarray(n1), np.asarray(n2), atol=1e-5
        )

    def test_auto_row_chunks_budget_holds_for_odd_n(self):
        """Regression (ISSUE 2 satellite): an odd / non-power-of-two-
        divisible n used to silently return 1 chunk, letting the (n, k)
        distance buffer blow past the element budget.  The budget is a
        hard bound now."""
        from oap_mllib_tpu.ops.kmeans_ops import auto_row_chunks

        budget = 4096
        for n in (1001, 999_999, 2**15 + 1):
            chunks = auto_row_chunks(n, 64, budget_elems=budget)
            assert chunks > 1
            assert (-(-n // chunks)) * 64 <= budget, (n, chunks)
        # small fits still take the no-scan-overhead single chunk
        assert auto_row_chunks(1000, 4) == 1

    def test_slot_chunk_size_matches_brute_force(self):
        """The O(sqrt cap) paired-divisor enumeration must agree with
        the old exhaustive scan: largest divisor of cap <= target."""
        from oap_mllib_tpu.ops.kmeans_ops import _slot_chunk_size

        for cap in list(range(1, 700, 13)) + [1024, 1536, 2048, 4100]:
            for target in (1, 7, 64, 1024):
                brute = max(
                    c for c in range(1, cap + 1)
                    if cap % c == 0 and c <= target
                ) if cap >= 1 else 1
                assert _slot_chunk_size(cap, target) == brute, (cap, target)

    def test_bad_precision_string_raises(self, rng):
        import jax.numpy as jnp
        from oap_mllib_tpu.ops.kmeans_ops import lloyd_run

        x = jnp.asarray(rng.normal(size=(8, 2)), jnp.float32)
        w = jnp.ones((8,), jnp.float32)
        with pytest.raises(ValueError):
            lloyd_run(x, w, x[:2], 2, jnp.asarray(0.0, jnp.float32), 1, "Highest")

    def test_cluster_sizes_in_summary(self, rng):
        x, _, assign = _blobs(rng, n=400, k=4)
        m = KMeans(k=4, max_iter=30, tol=1e-6, seed=7).fit(x)
        sizes = m.summary.cluster_sizes
        assert sizes is not None and sizes.shape == (4,)
        assert int(sizes.sum()) == 400
        # blob sizes recovered (order-insensitive)
        np.testing.assert_array_equal(
            np.sort(sizes.astype(int)), np.sort(np.bincount(assign)))

    def test_pmml_export(self, tmp_path, rng):
        import xml.etree.ElementTree as ET

        x, _, _ = _blobs(rng, k=3)
        m = KMeans(k=3, seed=1).fit(x)
        p = str(tmp_path / "model.pmml")
        m.to_pmml(p)
        tree = ET.parse(p)
        ns = {"p": "http://www.dmg.org/PMML-4_3"}
        cm = tree.getroot().find("p:ClusteringModel", ns)
        assert cm is not None and cm.get("numberOfClusters") == "3"
        clusters = cm.findall("p:Cluster", ns)
        assert len(clusters) == 3
        arr = clusters[0].find("p:Array", ns)
        vals = [float(v) for v in arr.text.split()]
        np.testing.assert_allclose(vals, m.cluster_centers_[0])
