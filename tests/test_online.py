"""Incremental fit paths (ISSUE 20): mini-batch Lloyd, streaming PCA,
ALS fold-in.

Contracts under test:

- mini-batch Lloyd from zero accumulated counts IS one Lloyd step over
  the batch (the count-weighted rule degenerates to the batch mean),
  and the decayed counts carry across deltas;
- IncrementalPCA over any chunking of the data matches the batch
  streamed PCA spectrum (same covariance convention, same solver);
- a folded-in ALS row is the EXACT regularized normal-equation solve
  against the frozen opposite table (Spark-parity weighting, both
  feedback modes), the axis grows with untouched new rows at the
  deterministic init, and fold-in approximates a from-scratch refit;
- every path is compute-then-swap: an injected ``delta.ingest`` /
  ``delta.solve`` fault leaves the model bit-identical.
"""

from __future__ import annotations

import numpy as np
import pytest

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.fallback import als_np
from oap_mllib_tpu.models.als import ALS, ALSModel
from oap_mllib_tpu.models.kmeans import KMeans, KMeansModel
from oap_mllib_tpu.models.pca import PCA
from oap_mllib_tpu.online import IncrementalPCA
from oap_mllib_tpu.telemetry import metrics as tm
from oap_mllib_tpu.utils.faults import FaultInjected


@pytest.fixture
def rng():
    return np.random.default_rng(7)


# ---------------------------------------------------------------------------
# mini-batch Lloyd
# ---------------------------------------------------------------------------


class TestPartialFitKMeans:
    def test_zero_counts_is_one_lloyd_step(self, rng):
        """With no accumulated counts the decayed update degenerates to
        the plain batch mean per assigned center — exactly one Lloyd
        step from the current centers."""
        centers = rng.normal(size=(4, 6)).astype(np.float32)
        x = rng.normal(size=(300, 6)).astype(np.float32)
        m = KMeansModel(centers.copy())
        m.partial_fit(x)
        d = ((x[:, None, :] - centers[None]) ** 2).sum(-1)
        assign = d.argmin(1)
        expect = centers.copy()
        for c in range(4):
            sel = assign == c
            if sel.any():
                expect[c] = x[sel].mean(0)
        np.testing.assert_allclose(m.cluster_centers_, expect, atol=1e-5)

    def test_counts_carry_and_weight_later_deltas(self, rng):
        """Second delta's update is count-weighted: a center that has
        already absorbed many rows moves less than a fresh one."""
        centers = np.array([[0.0], [10.0]], np.float32)
        m = KMeansModel(centers.copy())
        m.partial_fit(np.full((100, 1), 1.0, np.float32))
        c_after_1 = float(m.cluster_centers_[0, 0])
        assert c_after_1 == pytest.approx(1.0, abs=1e-5)
        m.partial_fit(np.full((100, 1), 3.0, np.float32))
        # 100 rows at mean 1 + 100 at 3 -> 2.0 under decay=1
        assert float(m.cluster_centers_[0, 0]) == pytest.approx(2.0, abs=1e-4)
        assert float(m.cluster_centers_[1, 0]) == pytest.approx(10.0)

    def test_decay_forgets_history(self, rng):
        set_config(online_decay=0.5)
        m = KMeansModel(np.array([[0.0]], np.float32))
        m.partial_fit(np.full((100, 1), 1.0, np.float32))
        m.partial_fit(np.full((100, 1), 3.0, np.float32))
        # n_eff = 50 at mean 1, 100 at 3 -> (50*1 + 300)/150
        assert float(m.cluster_centers_[0, 0]) == pytest.approx(
            (50 * 1.0 + 100 * 3.0) / 150, abs=1e-4
        )

    def test_seeds_counts_from_batch_fit_sizes(self, rng):
        """After a batch fit the summary cluster sizes ARE the starting
        counts — the first delta does not stomp the fitted centers."""
        x = rng.normal(size=(4000, 3)).astype(np.float32)
        m = KMeans(k=2, seed=1, max_iter=8).fit(x)
        before = m.cluster_centers_.copy()
        m.partial_fit(x[:8])  # tiny delta vs 4000 accumulated rows
        assert np.abs(m.cluster_centers_ - before).max() < 0.05

    def test_sample_weight(self, rng):
        m = KMeansModel(np.array([[0.0]], np.float32))
        x = np.array([[1.0], [5.0]], np.float32)
        m.partial_fit(x, sample_weight=np.array([3.0, 1.0]))
        assert float(m.cluster_centers_[0, 0]) == pytest.approx(2.0, abs=1e-5)

    def test_decay_typo_raises(self):
        set_config(online_decay=0.0)
        m = KMeansModel(np.zeros((2, 2), np.float32))
        with pytest.raises(ValueError, match="online_decay"):
            m.partial_fit(np.zeros((4, 2), np.float32))

    def test_width_mismatch_raises(self):
        m = KMeansModel(np.zeros((2, 3), np.float32))
        with pytest.raises(ValueError, match="width"):
            m.partial_fit(np.zeros((4, 2), np.float32))

    def test_fault_leaves_model_untouched(self, rng):
        x = rng.normal(size=(100, 4)).astype(np.float32)
        m = KMeansModel(rng.normal(size=(3, 4)).astype(np.float32))
        before = m.cluster_centers_.copy()
        set_config(fault_spec="delta.ingest:err=1")
        with pytest.raises(FaultInjected):
            m.partial_fit(x)
        np.testing.assert_array_equal(m.cluster_centers_, before)
        assert not hasattr(m, "_online_counts")
        # the armed count is spent: the retry succeeds
        m.partial_fit(x)
        assert np.abs(m.cluster_centers_ - before).max() > 0

    def test_books_delta_telemetry(self, rng):
        before = tm.family_total("oap_online_commits_total")
        rows_before = tm.family_total("oap_online_delta_rows_total")
        m = KMeansModel(rng.normal(size=(2, 3)).astype(np.float32))
        m.partial_fit(rng.normal(size=(50, 3)).astype(np.float32))
        assert tm.family_total("oap_online_commits_total") == before + 1
        assert (
            tm.family_total("oap_online_delta_rows_total")
            == rows_before + 50
        )


# ---------------------------------------------------------------------------
# incremental PCA
# ---------------------------------------------------------------------------


class TestIncrementalPCA:
    def test_matches_batch_pca_any_chunking(self, rng):
        x = rng.normal(size=(600, 10)).astype(np.float32)
        x[:, 0] *= 4.0  # a dominant direction
        ref = PCA(3).fit(x)
        ip = IncrementalPCA(3)
        for lo in (0, 100, 350):
            hi = {0: 100, 100: 350, 350: 600}[lo]
            ip.partial_fit(x[lo:hi])
        m = ip.commit()
        np.testing.assert_allclose(
            m.explained_variance_, ref.explained_variance_, atol=1e-5
        )
        # components match up to sign
        align = np.abs((m.components_ * ref.components_).sum(0))
        np.testing.assert_allclose(align, 1.0, atol=1e-4)

    def test_second_commit_updates_same_model_inplace(self, rng):
        ip = IncrementalPCA(2)
        ip.partial_fit(rng.normal(size=(200, 5)).astype(np.float32))
        m1 = ip.commit()
        comps1 = m1.components_
        ip.partial_fit(
            (rng.normal(size=(200, 5)) + [3, 0, 0, 0, 0]).astype(np.float32)
        )
        m2 = ip.commit()
        assert m2 is m1  # same object: serving handles re-pin in place
        assert m1.components_ is not comps1  # fresh array: pin re-stages
        assert m1.summary["online"]["commits"] == 2
        assert m1.summary["online"]["n_rows"] == 400

    def test_commit_before_fit_raises(self):
        with pytest.raises(ValueError, match="partial_fit"):
            IncrementalPCA(2).commit()

    def test_width_mismatch_raises(self, rng):
        ip = IncrementalPCA(2)
        ip.partial_fit(rng.normal(size=(50, 4)))
        with pytest.raises(ValueError, match="dimensionality"):
            ip.partial_fit(rng.normal(size=(50, 5)))

    def test_k_exceeds_d_raises(self, rng):
        ip = IncrementalPCA(6)
        ip.partial_fit(rng.normal(size=(50, 4)))
        with pytest.raises(ValueError, match="dimensionality"):
            ip.commit()

    def test_fault_leaves_accumulators_untouched(self, rng):
        x = rng.normal(size=(300, 4)).astype(np.float32)
        ip = IncrementalPCA(2)
        ip.partial_fit(x)
        ref = np.array(ip._gram), ip._n
        set_config(fault_spec="delta.ingest:err=1")
        with pytest.raises(FaultInjected):
            ip.partial_fit(x)
        np.testing.assert_array_equal(ip._gram, ref[0])
        assert ip._n == ref[1]


# ---------------------------------------------------------------------------
# ALS fold-in
# ---------------------------------------------------------------------------


def _fit_als(rng, nu=40, ni=30, rank=4, implicit=False, **kw):
    u = rng.integers(0, nu, size=2500)
    i = rng.integers(0, ni, size=2500)
    r = rng.normal(1.0, 0.6, size=2500).astype(np.float32)
    if implicit:
        r = np.abs(r)
    model = ALS(
        rank=rank, max_iter=6, reg_param=0.1, seed=5,
        implicit_prefs=implicit, alpha=0.8 if implicit else 1.0, **kw
    ).fit(u, i, r, n_users=nu, n_items=ni)
    return model, (u, i, r)


def _exact_row_explicit(y, items, ratings, reg, rank):
    yu = y[items]
    a = yu.T @ yu + reg * len(ratings) * np.eye(rank)
    return np.linalg.solve(a, yu.T @ ratings)


class TestALSFoldIn:
    def test_existing_user_row_is_exact_normal_eq_solve(self, rng):
        model, _ = _fit_als(rng)
        y = np.asarray(model.item_factors_, np.float64)
        items = np.arange(8)
        vals = rng.normal(1.0, 0.5, size=8).astype(np.float32)
        out = model.fold_in_users(np.full(8, 3), items, vals)
        assert out["rows_solved"] == 1 and out["grown"] is None
        expect = _exact_row_explicit(y, items, vals.astype(np.float64),
                                     0.1, 4)
        np.testing.assert_allclose(
            model.user_factors_[3], expect, atol=1e-4
        )

    def test_implicit_row_matches_spark_weighting(self, rng):
        model, _ = _fit_als(rng, implicit=True)
        y = np.asarray(model.item_factors_, np.float64)
        items = np.arange(6)
        vals = rng.uniform(0.5, 2.0, size=6).astype(np.float32)
        model.fold_in_users(np.full(6, 1), items, vals)
        alpha = 0.8
        yu = y[items]
        cw = alpha * np.abs(vals)
        a = (yu * cw[:, None]).T @ yu + y.T @ y \
            + 0.1 * len(items) * np.eye(4)
        b = yu.T @ (1.0 + cw)  # all ratings positive here
        np.testing.assert_allclose(
            model.user_factors_[1], np.linalg.solve(a, b), atol=1e-4
        )

    def test_grows_axis_untouched_rows_at_init(self, rng):
        model, _ = _fit_als(rng, nu=40)
        old = model.user_factors_.copy()
        items = np.arange(5)
        # touch user 44; users 40-43 and 45-49 appear only via growth
        out = model.fold_in_users(
            np.full(5, 44), items,
            rng.normal(1.0, 0.5, size=5).astype(np.float32),
            seed=5,
        )
        assert out["grown"] == [40, 45]
        assert model.user_factors_.shape == (45, 4)
        np.testing.assert_array_equal(model.user_factors_[:40], old)
        expect_init = als_np.init_factors_rows(40, 45, 4, 5)
        np.testing.assert_array_equal(
            model.user_factors_[40:44], expect_init[:4]
        )
        # the touched new row was SOLVED, not left at init
        assert np.abs(model.user_factors_[44] - expect_init[4]).max() > 0

    def test_item_side_symmetric(self, rng):
        model, _ = _fit_als(rng, ni=30)
        x = np.asarray(model.user_factors_, np.float64)
        users = np.arange(7)
        vals = rng.normal(1.0, 0.5, size=7).astype(np.float32)
        out = model.fold_in_items(users, np.full(7, 33), vals, seed=5)
        assert out["side"] == "item" and out["grown"] == [30, 34]
        expect = _exact_row_explicit(x, users, vals.astype(np.float64),
                                     0.1, 4)
        np.testing.assert_allclose(
            model.item_factors_[33], expect, atol=1e-4
        )
        # untouched grown item rows take the seed+1 init stream
        np.testing.assert_array_equal(
            model.item_factors_[30:33],
            als_np.init_factors_rows(30, 33, 4, 6),
        )

    def test_batched_matches_single_launch(self, rng):
        model_a, _ = _fit_als(rng)
        model_b = ALSModel(
            model_a.user_factors_.copy(), model_a.item_factors_.copy(),
            dict(model_a.summary),
        )
        rng2 = np.random.default_rng(3)
        users = rng2.integers(0, 40, size=60)
        items = rng2.integers(0, 30, size=60)
        vals = rng2.normal(1.0, 0.5, size=60).astype(np.float32)
        model_a.fold_in_users(users, items, vals)
        set_config(online_foldin_batch=3)
        model_b.fold_in_users(users, items, vals)
        np.testing.assert_allclose(
            model_a.user_factors_, model_b.user_factors_, atol=1e-5
        )

    def test_foldin_approximates_refit(self, rng):
        """Fold-in of a few new users over a large base approximates the
        from-scratch refit on the combined ratings.  Parity is measured
        on PREDICTIONS (new users x all items), not raw factors — an
        ALS factorization is only unique up to an invertible transform
        applied oppositely to X and Y, so a fresh refit lands on a
        rotated basis whose raw rows are incomparable.  The documented
        bound (docs/user-guide.md): relative Frobenius error < 0.15."""
        model, (u, i, r) = _fit_als(rng, nu=40, ni=30)
        rng2 = np.random.default_rng(9)
        nu_new = 4
        un = np.repeat(np.arange(40, 40 + nu_new), 20)
        un_items = rng2.integers(0, 30, size=20 * nu_new)
        un_vals = rng2.normal(1.0, 0.6, size=20 * nu_new).astype(np.float32)
        model.fold_in_users(un, un_items, un_vals)
        refit = ALS(rank=4, max_iter=6, reg_param=0.1, seed=5).fit(
            np.concatenate([u, un]), np.concatenate([i, un_items]),
            np.concatenate([r, un_vals]), n_users=40 + nu_new, n_items=30,
        )
        pred_fold = model.user_factors_[40:] @ model.item_factors_.T
        pred_refit = refit.user_factors_[40:] @ refit.item_factors_.T
        rel = (
            np.linalg.norm(pred_fold - pred_refit)
            / np.linalg.norm(pred_refit)
        )
        assert rel < 0.15  # docs/user-guide.md parity bound

    def test_defaults_come_from_fit_params(self, rng):
        model, _ = _fit_als(rng)
        assert model.summary["params"]["reg"] == pytest.approx(0.1)
        # a bare model (no params) demands an explicit reg
        bare = ALSModel(
            model.user_factors_.copy(), model.item_factors_.copy()
        )
        with pytest.raises(ValueError, match="reg"):
            bare.fold_in_users([0], [0], [1.0])
        bare.fold_in_users([0], [0], [1.0], reg=0.1)  # explicit works

    def test_validation_errors(self, rng):
        model, _ = _fit_als(rng)
        with pytest.raises(ValueError, match="side"):
            from oap_mllib_tpu.online import foldin

            foldin.fold_in(model, [0], [0], [1.0], side="row")
        with pytest.raises(ValueError, match="frozen-side"):
            model.fold_in_users([0], [99], [1.0])  # item 99 of 30
        with pytest.raises(ValueError, match="lengths"):
            model.fold_in_users([0, 1], [0], [1.0])
        with pytest.raises(ValueError, match="at least one"):
            model.fold_in_users([], [], [])

    def test_solve_fault_leaves_model_untouched(self, rng):
        model, _ = _fit_als(rng)
        before_u = model.user_factors_.copy()
        set_config(fault_spec="delta.solve:err=1")
        with pytest.raises(FaultInjected):
            model.fold_in_users([50, 50], [0, 1], [1.0, 2.0])
        np.testing.assert_array_equal(model.user_factors_, before_u)
        assert model.user_factors_.shape == (40, 4)  # no growth either

    def test_ingest_fault_leaves_model_untouched(self, rng):
        model, _ = _fit_als(rng)
        before_u = model.user_factors_.copy()
        set_config(fault_spec="delta.ingest:err=1")
        with pytest.raises(FaultInjected):
            model.fold_in_users([0], [0], [1.0])
        np.testing.assert_array_equal(model.user_factors_, before_u)
