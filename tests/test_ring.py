"""ISSUE 9 ring-reduction tests (8-device CPU pseudo-cluster): the
ppermute-schedule ring vs the psum reference, the clean <2-device
fallback, the default ring-fused model-sharded Lloyd, and the collective
census proving the standalone per-pass centroid allreduces are gone.

The remote-DMA TPU kernel shares the exact segment schedule tested here
(ops/pallas/ring_reduce module notes); its compiled leg lives in
``tests_tpu/test_kernels_tpu.py``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.ops import kmeans_ops
from oap_mllib_tpu.ops.pallas.ring_reduce import (
    ring_allreduce,
    stacked_ring_fn,
)
from oap_mllib_tpu.parallel.mesh import get_mesh
from oap_mllib_tpu.telemetry import metrics as tm
from oap_mllib_tpu.utils.jax_compat import shard_map


def _mesh8():
    return jax.make_mesh((8,), ("data",))


def _ring_program(mesh, world):
    def body(blk):
        return ring_allreduce(blk[0], "data", world)[None]

    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=P("data", None, None),
            out_specs=P("data", None, None), check_vma=False,
        )
    )


class TestRingAllreduce:
    @pytest.mark.parametrize(
        "rows,cols", [(13, 37), (3, 5), (8, 256), (1, 1), (40, 130)]
    )
    def test_matches_sum_and_is_rank_identical(self, rng, rows, cols):
        mesh = _mesh8()
        g = rng.normal(size=(8, rows, cols)).astype(np.float32)
        gd = jax.device_put(
            jnp.asarray(g), NamedSharding(mesh, P("data", None, None))
        )
        out = np.asarray(_ring_program(mesh, 8)(gd))
        ref = g.sum(axis=0)
        np.testing.assert_allclose(out[0], ref, atol=2e-5)
        for i in range(1, 8):
            assert np.array_equal(out[0], out[i])  # deterministic ring

    def test_matches_psum_reference_1e5(self, rng):
        """The acceptance bound: ring vs the psum path at 1e-5 on the
        8-device virtual mesh."""
        mesh = _mesh8()
        g = rng.normal(size=(8, 50, 70)).astype(np.float32) * 10.0
        gd = jax.device_put(
            jnp.asarray(g), NamedSharding(mesh, P("data", None, None))
        )
        ring = np.asarray(_ring_program(mesh, 8)(gd))[0]
        from oap_mllib_tpu.parallel import collective

        psum_fn = jax.jit(
            shard_map(
                lambda b: collective.psum(b[0], "data")[None],
                mesh=mesh, in_specs=P("data", None, None),
                out_specs=P("data", None, None), check_vma=False,
            )
        )
        ref = np.asarray(psum_fn(gd))[0]
        np.testing.assert_allclose(
            ring, ref, rtol=1e-5, atol=1e-5 * np.abs(ref).max()
        )

    def test_world_one_falls_back_to_psum(self, rng):
        mesh1 = jax.make_mesh((1,), ("data",), devices=jax.devices()[:1])
        g = rng.normal(size=(1, 6, 4)).astype(np.float32)
        gd = jax.device_put(
            jnp.asarray(g), NamedSharding(mesh1, P("data", None, None))
        )
        out = np.asarray(_ring_program(mesh1, 1)(gd))
        assert np.array_equal(out[0], g[0])

    def test_stacked_entry_registry_cached(self, rng):
        mesh = _mesh8()
        fn1 = stacked_ring_fn(mesh, "data")
        fn2 = stacked_ring_fn(mesh, "data")
        assert fn1 is fn2  # progcache get_or_build hit
        g = rng.normal(size=(8, 9, 11)).astype(np.float32)
        gd = jax.device_put(
            jnp.asarray(g), NamedSharding(mesh, P("data", None, None))
        )
        out = np.asarray(fn1(gd))
        np.testing.assert_allclose(out[3], g.sum(0), atol=2e-5)


class TestRingEnabled:
    def test_resolution_and_fallback(self):
        mesh = get_mesh()
        assert kmeans_ops.ring_enabled(mesh, "data")  # default auto, 8 dev
        set_config(ring_reduction="off")
        assert not kmeans_ops.ring_enabled(mesh, "data")
        set_config(ring_reduction="on")
        assert kmeans_ops.ring_enabled(mesh, "data")
        mesh1 = get_mesh(n_devices=1)
        assert not kmeans_ops.ring_enabled(mesh1, "data")  # <2 devices

    def test_typo_raises(self):
        set_config(ring_reduction="ring")
        with pytest.raises(ValueError, match="ring_reduction"):
            kmeans_ops.ring_enabled(get_mesh(), "data")


class TestModelShardedRing:
    def _fit(self, rng, max_iter, seed=0):
        n, d, k = 512, 16, 5
        data_rng = np.random.default_rng(seed)
        x = data_rng.normal(size=(n, d)).astype(np.float32)
        w = np.ones((n,), np.float32)
        c0 = x[data_rng.choice(n, k, replace=False)]
        mesh = get_mesh()
        xs = jax.device_put(
            jnp.asarray(x), NamedSharding(mesh, P("data", "model"))
        )
        ws = jax.device_put(jnp.asarray(w), NamedSharding(mesh, P("data")))
        tol = jnp.asarray(1e-6, jnp.float32)
        return kmeans_ops.lloyd_run_model_sharded(
            xs, ws, jnp.asarray(c0), max_iter, tol, mesh, "data", "model"
        )

    def test_ring_default_matches_psum_path(self, rng):
        set_config(model_parallel=2)
        c_r, it_r, cost_r, cnt_r = self._fit(rng, 20)
        set_config(ring_reduction="off")
        c_p, it_p, cost_p, cnt_p = self._fit(rng, 20)
        assert int(it_r) == int(it_p)
        np.testing.assert_allclose(
            np.asarray(c_r), np.asarray(c_p), atol=1e-5
        )
        np.testing.assert_allclose(float(cost_r), float(cost_p), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(cnt_r), np.asarray(cnt_p), atol=1e-3
        )

    def test_census_zero_standalone_centroid_allreduces(self, rng):
        """The acceptance assertion, via the trace-time collective
        census: building the ring-fused Lloyd emits psum ONLY for the
        model-axis assignment reduction (loop body + final cost pass)
        and the convergence move — the three standalone centroid-moment
        psums (sums, counts, cost) are gone, replaced by ring ppermutes
        and booked as ring.allreduce kernel emissions."""
        set_config(model_parallel=2)  # (data=4, model=2) mesh
        psum_c = tm.counter("oap_collective_emitted_total", {"op": "psum"})
        perm_c = tm.counter(
            "oap_collective_emitted_total", {"op": "ppermute"}
        )
        ring_c = tm.counter(
            "oap_kernel_emitted_total", {"kernel": "ring.allreduce"}
        )
        p0, q0, r0 = psum_c.value, perm_c.value, ring_c.value
        self._fit(rng, 23)  # unique max_iter -> fresh program build
        psums = psum_c.value - p0
        # score psum (loop accum) + d2 psum (final accum) + move psum
        assert psums == 3, psums
        assert ring_c.value - r0 == 2  # loop + final-pass rings
        # bi-directional ring: 2 directions x 2*(world-1) steps per ring
        assert perm_c.value - q0 == 2 * (2 * 2 * (4 - 1))

    def test_ring_off_build_emits_moment_psums(self, rng):
        set_config(model_parallel=2, ring_reduction="off")
        psum_c = tm.counter("oap_collective_emitted_total", {"op": "psum"})
        p0 = psum_c.value
        self._fit(rng, 29)
        # score + sums + counts (loop) / d2 + sums + counts + cost
        # (final) / move
        assert psum_c.value - p0 == 8

    def test_x64_lane_keeps_psum_path(self, rng):
        """The ring packs f32; the x64 parity lane must resolve to the
        psum path (ring flag off for f64 inputs) without error."""
        set_config(model_parallel=2)
        from oap_mllib_tpu.utils.timing import x64_scope

        with x64_scope(True):
            n, d, k = 64, 8, 3
            x = rng.normal(size=(n, d)).astype(np.float64)
            mesh = get_mesh()
            xs = jax.device_put(
                jnp.asarray(x), NamedSharding(mesh, P("data", "model"))
            )
            ws = jax.device_put(
                jnp.ones((n,)), NamedSharding(mesh, P("data"))
            )
            c, it, cost, cnt = kmeans_ops.lloyd_run_model_sharded(
                xs, ws, jnp.asarray(x[:k]), 5,
                jnp.asarray(1e-6, jnp.float64), mesh, "data", "model",
            )
            assert np.asarray(c).dtype == np.float64
            assert np.isfinite(float(cost))


class TestStreamedRingRoute:
    def test_single_process_identity_unchanged(self):
        from oap_mllib_tpu.ops import stream_ops

        arrays = [
            np.ones((3, 4), np.float32), np.asarray([7], np.int64)
        ]
        out = stream_ops._psum_host(arrays)
        assert np.array_equal(out[0], arrays[0])
        assert np.array_equal(out[1], arrays[1])
        assert stream_ops._ring_mesh() is None  # world == 1

    def test_ring_reduce_f32_packs_and_unpacks(self, rng):
        """Single-process exercise of the packed-sheet shape logic
        through the stacked ring program on the 8-device mesh (the
        multi-process leg rides the pseudo-cluster suite)."""
        from oap_mllib_tpu.ops import stream_ops

        mesh = get_mesh()
        sums = rng.normal(size=(5, 7)).astype(np.float32)
        counts = rng.normal(size=(5,)).astype(np.float32)
        cost = np.float32(3.25)
        out = stream_ops._ring_reduce_f32(
            [sums, counts, cost], mesh, "data"
        )
        # one process contributing -> the sum IS the payload
        np.testing.assert_allclose(out[0], sums, atol=1e-6)
        np.testing.assert_allclose(out[1], counts, atol=1e-6)
        np.testing.assert_allclose(out[2], cost, atol=1e-6)
        assert out[0].shape == sums.shape and out[1].shape == counts.shape
