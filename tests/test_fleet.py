"""Fleet control plane units (ISSUE 11): knob validation, frame
building, rollup folds, straggler analytics, the summary block, and the
live /metrics + /healthz endpoints."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.data.prefetch import PrefetchStats
from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.models.kmeans import KMeans
from oap_mllib_tpu.parallel.bootstrap import free_port
from oap_mllib_tpu.telemetry import fleet
from oap_mllib_tpu.telemetry import metrics as tm


@pytest.fixture(autouse=True)
def _clean():
    set_config(fleet_stats="auto", metrics_port=0, flight_recorder=0)
    fleet._reset_for_tests()
    yield
    set_config(fleet_stats="auto", metrics_port=0, flight_recorder=0)
    fleet._reset_for_tests()


def _source(rows=1200, d=6, chunk=300, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(rows, d)).astype(np.float32)

    def gen():
        for lo in range(0, rows, chunk):
            yield x[lo:lo + chunk]

    return ChunkSource(gen, d, chunk, n_rows=rows)


class TestKnobs:
    def test_fleet_stats_modes(self):
        assert fleet.armed(1) is False  # auto, single process
        assert fleet.armed(2) is True  # auto, world
        set_config(fleet_stats="on")
        assert fleet.armed(1) is True
        set_config(fleet_stats="off")
        assert fleet.armed(8) is False

    def test_fleet_stats_typo_raises(self):
        set_config(fleet_stats="onn")
        with pytest.raises(ValueError, match="fleet_stats"):
            fleet.armed(2)

    def test_metrics_port_negative_raises(self):
        set_config(metrics_port=-1)
        with pytest.raises(ValueError, match="metrics_port"):
            fleet.maybe_serve()


class TestFrames:
    def test_local_frame_shape_and_contents(self):
        stats = PrefetchStats()
        stats.stage_s, stats.transfer_s, stats.wait_s = 0.2, 0.05, 0.1
        stats.bytes_staged = 4096
        frame = fleet.local_frame(stats, 1.0)
        assert frame.shape == (len(fleet.FRAME_FIELDS),)
        assert frame.dtype == np.float64
        named = dict(zip(fleet.FRAME_FIELDS, frame))
        assert named["pass_wall_s"] == 1.0
        assert named["stage_s"] == pytest.approx(0.2)
        assert named["transfer_s"] == pytest.approx(0.05)
        assert named["compute_s"] == pytest.approx(0.9)  # wall - wait
        assert named["bytes_staged"] == 4096

    def test_fold_rejects_wrong_shape(self):
        with pytest.raises(ValueError, match="frame shape"):
            fleet.fold_pass("p", np.zeros((3, 2)))

    def test_fold_matches_hand_fold(self):
        rng = np.random.default_rng(3)
        frames = rng.random((4, len(fleet.FRAME_FIELDS)))
        rec = fleet.fold_pass("p", frames)
        for i, f in enumerate(fleet.FRAME_FIELDS):
            col = frames[:, i]
            assert rec["fields"][f]["min"] == pytest.approx(col.min())
            assert rec["fields"][f]["max"] == pytest.approx(col.max())
            assert rec["fields"][f]["mean"] == pytest.approx(col.mean())
            assert rec["fields"][f]["p99"] == pytest.approx(
                np.percentile(col, 99)
            )

    def test_fold_books_fleet_metrics_with_stats_labels(self):
        frames = np.ones((2, len(fleet.FRAME_FIELDS)))
        frames[1, 0] = 3.0
        fleet.fold_pass("p", frames)
        text = tm.render_prometheus()
        assert 'oap_fleet_pass_seconds{stat="max"} 3' in text
        assert 'oap_fleet_pass_seconds{stat="min"} 1' in text
        assert "oap_fleet_skew_ratio 1.5" in text
        assert "oap_fleet_slowest_rank 1" in text
        assert "oap_fleet_pass_wall_seconds_bucket" in text


class TestStragglerAnalytics:
    def test_skewed_rank_named(self):
        frames = np.ones((4, len(fleet.FRAME_FIELDS)))
        frames[2, 0] = 5.0
        rec = fleet.fold_pass("lloyd_loop", frames)
        assert rec["slowest_rank"] == 2
        assert rec["skew_ratio"] == pytest.approx(5.0 / 2.0)

    def test_summary_block_aggregates_across_passes(self):
        even = np.ones((2, len(fleet.FRAME_FIELDS)))
        slow = even.copy()
        slow[1, 0] = 4.0
        for _ in range(3):
            fleet.fold_pass("p", slow)
        block = fleet.summary_block()
        assert block["passes"] == 3
        assert block["slowest_rank"] == 1
        assert block["fit_skew_ratio"] > 1.5
        assert block["per_rank_pass_s"][1] == pytest.approx(12.0)

    def test_imbalance_trend(self):
        assert fleet._trend([1.0, 1.0, 1.0, 1.0]) == "flat"
        assert fleet._trend([1.0, 1.0, 1.5, 1.6]) == "rising"
        assert fleet._trend([1.6, 1.5, 1.0, 1.0]) == "falling"
        assert fleet._trend([1.0]) == "flat"  # too short to call


class TestFitIntegration:
    def test_streamed_fit_lands_fleet_block_and_span(self):
        set_config(fleet_stats="on")
        m = KMeans(k=3, seed=0, init_mode="random", max_iter=3,
                   tol=0.0).fit(_source())
        block = m.summary.fleet
        assert block["enabled"] is True
        assert block["world"] == 1
        # per-pass granularity: >= max_iter passes (+ the final
        # cost/counts pass)
        assert block["passes"] >= 3
        assert block["slowest_rank"] == 0
        assert block["skew_ratio"] == pytest.approx(1.0)
        spans = m.summary.telemetry["spans"]
        names = [c["name"] for c in spans["children"]]
        assert "fleet" in names
        fleet_span = next(c for c in spans["children"]
                          if c["name"] == "fleet")
        assert fleet_span["attrs"]["passes"] == block["passes"]

    def test_window_resets_between_fits(self):
        set_config(fleet_stats="on")
        KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(_source())
        assert fleet.last_window() == []  # finalize drained it
        m = KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(
            _source()
        )
        assert m.summary.fleet["passes"] >= 2

    def test_disarmed_fit_has_no_fleet_block(self):
        set_config(fleet_stats="off")
        m = KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(
            _source()
        )
        assert not hasattr(m.summary, "fleet")

    def test_streamed_pca_collects_passes(self):
        from oap_mllib_tpu.models.pca import PCA

        set_config(fleet_stats="on")
        summary = {}
        model = PCA(k=2).fit(_source(seed=5))
        block = model.summary.get("fleet") if isinstance(
            model.summary, dict) else model.summary.fleet
        assert block["passes"] >= 2  # colsum + gram
        del summary


class TestLiveEndpoints:
    def test_metrics_and_healthz_serve(self):
        port = free_port("127.0.0.1", 9500)
        set_config(fleet_stats="on", metrics_port=port, flight_recorder=64)
        m = KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(
            _source()
        )
        assert fleet.server_port() == port
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ).read().decode()
        assert "# TYPE oap_fleet_pass_seconds gauge" in text
        assert "oap_fit_total" in text
        hz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5
        ).read())
        assert hz["ok"] is True
        assert hz["fit"] == "kmeans.fit"
        assert hz["step"] >= 2
        assert hz["ladder"] == "active"
        assert hz["flight_recorder_seq"] >= 0
        assert "last_collective" in hz
        del m

    def test_unknown_path_404s(self):
        port = free_port("127.0.0.1", 9500)
        set_config(metrics_port=port)
        assert fleet.maybe_serve() == port
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=5
            )

    def test_port_zero_never_serves(self):
        assert fleet.maybe_serve() is None
        assert fleet.server_port() is None
