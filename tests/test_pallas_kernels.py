"""ISSUE 9 kernel-plane tests (interpret mode, CPU pseudo-cluster):
PCA fused moments + ALS batched normal-equation solve vs their XLA
references at every precision tier, plus the single-shot padding
regression for the K-Means kernel.

Compiled-mode legs live in ``tests_tpu/test_kernels_tpu.py`` (run by
dev/ci.sh when a TPU backend is present), so a Mosaic lowering
regression cannot ship green on this suite alone.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.ops import als_ops, stream_ops
from oap_mllib_tpu.ops.pallas.als_kernel import (
    factor_gram_pallas,
    pallas_solve_preferred,
    solve_normal_eq_pallas,
)
from oap_mllib_tpu.ops.pallas.pca_kernel import (
    covariance_pallas,
    pallas_gram_preferred,
    pca_moments_pallas,
)
from oap_mllib_tpu.ops.pca_ops import _covariance_jit, use_pallas_gram
from oap_mllib_tpu.utils import precision as psn
from oap_mllib_tpu.utils import progcache


# ---------------------------------------------------------------------------
# PCA fused moments
# ---------------------------------------------------------------------------


class TestPcaMomentsKernel:
    def _data(self, rng, n=900, d=33, mean=5.0):
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32) + mean)
        m = jnp.asarray((rng.random(n) < 0.95).astype(np.float32))
        return x, m

    def test_colsum_and_count_match_xla_bitwise(self, rng):
        """The mean-pass outputs are tier-independent exact f32 VPU
        reductions — single-tile inputs match the XLA colsum bitwise."""
        x, m = self._data(rng, n=512)
        _, cs, cnt = pca_moments_pallas(x, m, need_gram=False, interpret=True)
        ref = jnp.sum(x * m[:, None], axis=0)
        assert np.array_equal(np.asarray(cs), np.asarray(ref))
        assert float(cnt) == float(jnp.sum(m))

    def test_covariance_matches_xla_at_highest(self, rng):
        x, m = self._data(rng)
        nv = jnp.asarray(float(np.asarray(m).sum()))
        cov_p, mean_p = covariance_pallas(x, m, nv, interpret=True)
        cov_r, mean_r = _covariance_jit(x, m, nv)
        np.testing.assert_allclose(
            np.asarray(mean_p), np.asarray(mean_r), atol=2e-6
        )
        np.testing.assert_allclose(
            np.asarray(cov_p), np.asarray(cov_r), atol=2e-6
        )

    def test_bit_compatible_at_highest_on_exact_data(self, rng):
        """The "bit-compatible at highest" contract, on data where f32
        arithmetic is exact: small symmetric integer rows (mean exactly
        0, products and their sums exactly representable), so EVERY
        summation order yields identical bits — the kernel's tile
        accumulation must reproduce the XLA pass bit-for-bit.  On
        general data the two differ only by shape-dependent dot blocking
        (<= a few ulps, pinned by test_covariance_matches_xla)."""
        n, d = 1024, 17
        half = rng.integers(-3, 4, size=(n // 2, d)).astype(np.float32)
        x = jnp.asarray(np.concatenate([half, -half]))  # colsum == 0
        m = jnp.ones((n,), jnp.float32)
        nv = jnp.asarray(float(n))
        cov_p, mean_p = covariance_pallas(x, m, nv, interpret=True)
        cov_r, mean_r = _covariance_jit(x, m, nv)
        assert np.array_equal(np.asarray(mean_p), np.asarray(mean_r))
        assert np.array_equal(np.asarray(cov_p), np.asarray(cov_r))

    @pytest.mark.parametrize(
        "mode,alias,atol",
        [("high", "tf32", 5e-5), ("default", "bf16", 5e-3)],
    )
    def test_split_tiers_within_envelope(self, rng, mode, alias, atol):
        """The hand-rolled hi/lo tiers hold their envelopes, and the
        compute-policy aliases resolve to the same tier (what prices the
        bf16 policy ON Pallas)."""
        x, m = self._data(rng, mean=0.0)
        nv = jnp.asarray(float(np.asarray(m).sum()))
        cov_r, _ = _covariance_jit(x, m, nv)
        cov_t, _ = covariance_pallas(x, m, nv, mode=mode, interpret=True)
        np.testing.assert_allclose(
            np.asarray(cov_t), np.asarray(cov_r), atol=atol
        )
        cov_a, _ = covariance_pallas(x, m, nv, mode=alias, interpret=True)
        assert np.array_equal(np.asarray(cov_a), np.asarray(cov_t))

    def test_streamed_chunk_fns_match_xla(self, rng):
        """The streamed per-chunk accumulators (plain + Kahan) built on
        the kernel reproduce the XLA chunk fns exactly at highest."""
        x, m = self._data(rng, n=512)
        d = x.shape[1]
        mean = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))
        cs_p = stream_ops._colsum_chunk_pallas(
            jnp.zeros((d,), jnp.float32), x, m, interpret=True
        )
        cs_r = stream_ops._colsum_chunk(jnp.zeros((d,), jnp.float32), x, m)
        assert np.array_equal(np.asarray(cs_p), np.asarray(cs_r))
        g_p = stream_ops._gram_chunk_pallas(
            jnp.zeros((d, d), jnp.float32), x, m, mean, "highest",
            interpret=True,
        )
        g_r = stream_ops._gram_chunk(
            jnp.zeros((d, d), jnp.float32), x, m, mean, "highest"
        )
        # shape-dependent dot blocking (the kernel contracts the padded
        # 128-column tile) allows ulp-level drift; exact-data bit parity
        # is pinned in test_bit_compatible_at_highest_on_exact_data
        np.testing.assert_allclose(
            np.asarray(g_p), np.asarray(g_r),
            atol=1e-5 * max(1.0, float(np.abs(np.asarray(g_r)).max())),
        )
        # Kahan-compensated pair (the bf16 policy's cross-chunk contract)
        t, c = stream_ops._colsum_chunk_pallas_comp(
            jnp.zeros((d,), jnp.float32), jnp.zeros((d,), jnp.float32),
            x, m, interpret=True,
        )
        t_r, c_r = stream_ops._colsum_chunk_comp(
            jnp.zeros((d,), jnp.float32), jnp.zeros((d,), jnp.float32), x, m
        )
        assert np.array_equal(np.asarray(t), np.asarray(t_r))
        g2, gc2 = stream_ops._gram_chunk_pallas_comp(
            jnp.zeros((d, d), jnp.float32), jnp.zeros((d, d), jnp.float32),
            x, m, mean, "default", interpret=True,
        )
        assert np.isfinite(np.asarray(g2)).all()

    def test_bad_mode_and_bad_kernel_cfg_raise(self, rng):
        x, m = self._data(rng, n=64)
        with pytest.raises(ValueError, match="mode"):
            pca_moments_pallas(x, m, mode="fast", interpret=True)
        with pytest.raises(ValueError, match="pca_kernel"):
            use_pallas_gram("fastest", 8, "highest", np.float32)

    def test_dispatch_rule(self):
        # CPU backend: never dispatches, but the preference rule and the
        # validation run on every fit
        assert not use_pallas_gram("auto", 64, "highest", np.float32)
        assert pallas_gram_preferred(64, "default")  # bf16 ON pallas
        assert not pallas_gram_preferred(4096, "highest")  # VMEM bound

    def test_streamed_covariance_validates_kernel_cfg(self, rng):
        from oap_mllib_tpu.data.stream import ChunkSource

        set_config(pca_kernel="nope")
        data = rng.normal(size=(64, 5)).astype(np.float32)
        src = ChunkSource(
            lambda: iter([data]), n_features=5, chunk_rows=32, n_rows=64
        )
        with pytest.raises(ValueError, match="pca_kernel"):
            stream_ops.covariance_streamed(src, np.float32)


# ---------------------------------------------------------------------------
# ALS batched normal-equation solve
# ---------------------------------------------------------------------------


def _spd_batch(rng, n, r, reg_floor=0.5):
    m = rng.normal(size=(n, r, r)).astype(np.float32)
    a = jnp.asarray(np.einsum("nij,nkj->nik", m, m) + reg_floor * np.eye(r))
    b = jnp.asarray(rng.normal(size=(n, r)).astype(np.float32))
    n_reg = jnp.asarray(
        (rng.random(n) > 0.1).astype(np.float32) * rng.integers(1, 50, n)
    )
    return a, b, n_reg


class TestAlsSolveKernel:
    def test_matches_xla_solve_with_gram(self, rng):
        n, r = 700, 10
        a, b, n_reg = _spd_batch(rng, n, r)
        g = rng.normal(size=(40, r)).astype(np.float32)
        gram = jnp.asarray(g.T @ g * 0.01)
        eye = jnp.eye(r, dtype=jnp.float32)
        ref = als_ops.regularized_solve(a, b, n_reg, 0.1, eye, gram)
        out = solve_normal_eq_pallas(a, b, n_reg, 0.1, gram, interpret=True)
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(out), atol=2e-5
        )
        # empty rows (n_reg == 0) masked to exact zeros on both paths
        zero_rows = np.asarray(n_reg) == 0
        assert (np.asarray(out)[zero_rows] == 0).all()

    def test_matches_xla_solve_no_gram(self, rng):
        n, r = 300, 10
        a, b, n_reg = _spd_batch(rng, n, r)
        eye = jnp.eye(r, dtype=jnp.float32)
        ref = als_ops.regularized_solve(a, b, n_reg, 0.5, eye, None)
        out = solve_normal_eq_pallas(a, b, n_reg, 0.5, None, interpret=True)
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(out), atol=2e-5
        )

    @pytest.mark.parametrize("r", [1, 3, 32])
    def test_rank_edges(self, rng, r):
        a, b, n_reg = _spd_batch(rng, 40, r)
        eye = jnp.eye(r, dtype=jnp.float32)
        ref = als_ops.regularized_solve(a, b, n_reg, 0.5, eye, None)
        out = solve_normal_eq_pallas(a, b, n_reg, 0.5, None, interpret=True)
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(out), atol=5e-5
        )

    def test_rank_bound_raises(self, rng):
        r = 33
        a, b, n_reg = _spd_batch(rng, 8, r)
        with pytest.raises(ValueError, match="rank"):
            solve_normal_eq_pallas(a, b, n_reg, 0.5, None, interpret=True)
        assert not pallas_solve_preferred(r)
        assert pallas_solve_preferred(10)

    def test_factor_gram_tiers(self, rng):
        f = jnp.asarray(rng.normal(size=(777, 10)).astype(np.float32))
        ref = psn.pdot(f.T, f)
        out = factor_gram_pallas(f, interpret=True)
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(out), rtol=1e-6, atol=1e-3
        )
        for mode, rtol in (("high", 1e-4), ("default", 2e-2)):
            out_t = factor_gram_pallas(f, mode=mode, interpret=True)
            np.testing.assert_allclose(
                np.asarray(ref), np.asarray(out_t), rtol=rtol, atol=1e-1
            )

    def test_full_runner_parity_grouped_implicit(self, rng):
        """The whole ALS loop with the Pallas solve (interpret leg) stays
        within fp tolerance of the XLA-solve loop — the tier-1 proof that
        the fused consumer is a drop-in for every runner."""
        nu, ni, nnz, r = 300, 200, 4000, 8
        u = rng.integers(0, nu, nnz).astype(np.int64)
        i = rng.integers(0, ni, nnz).astype(np.int64)
        c = (rng.random(nnz) * 4 + 1).astype(np.float32)
        x0 = jnp.asarray((rng.normal(size=(nu, r)) * 0.1).astype(np.float32))
        y0 = jnp.asarray((rng.normal(size=(ni, r)) * 0.1).astype(np.float32))
        by_u = tuple(
            jnp.asarray(a) for a in als_ops.build_grouped_edges(u, i, c, nu)
        )
        by_i = tuple(
            jnp.asarray(a) for a in als_ops.build_grouped_edges(i, u, c, ni)
        )
        xa, ya = als_ops.als_run_grouped(
            *by_u, *by_i, x0, y0, nu, ni, 5, 0.1, 40.0, True,
            solve_kernel="xla",
        )
        xb, yb = als_ops.als_run_grouped(
            *by_u, *by_i, x0, y0, nu, ni, 5, 0.1, 40.0, True,
            solve_kernel="pallas_interpret",
        )
        np.testing.assert_allclose(
            np.asarray(xa), np.asarray(xb), atol=5e-4
        )
        np.testing.assert_allclose(
            np.asarray(ya), np.asarray(yb), atol=5e-4
        )

    def test_full_runner_parity_explicit_coo(self, rng):
        nu, ni, nnz, r = 200, 150, 3000, 6
        u = rng.integers(0, nu, nnz).astype(np.int32)
        i = rng.integers(0, ni, nnz).astype(np.int32)
        c = (rng.random(nnz) * 4 + 1).astype(np.float32)
        pad = (-nnz) % 2048
        uj = jnp.asarray(np.pad(u, (0, pad)))
        ij = jnp.asarray(np.pad(i, (0, pad)))
        rj = jnp.asarray(np.pad(c, (0, pad)))
        vj = jnp.asarray(np.pad(np.ones(nnz, np.float32), (0, pad)))
        x0 = jnp.asarray((rng.normal(size=(nu, r)) * 0.1).astype(np.float32))
        y0 = jnp.asarray((rng.normal(size=(ni, r)) * 0.1).astype(np.float32))
        xa, _ = als_ops.als_explicit_run(
            uj, ij, rj, vj, x0, y0, nu, ni, 4, 0.1, solve_kernel="xla"
        )
        xb, _ = als_ops.als_explicit_run(
            uj, ij, rj, vj, x0, y0, nu, ni, 4, 0.1,
            solve_kernel="pallas_interpret",
        )
        np.testing.assert_allclose(
            np.asarray(xa), np.asarray(xb), atol=5e-4
        )

    def test_resolve_solve_kernel(self):
        # CPU backend: auto resolves to the XLA path; typo raises
        assert als_ops.resolve_solve_kernel(10, np.float32) == "xla"
        set_config(als_solve_kernel="nope")
        with pytest.raises(ValueError, match="als_solve_kernel"):
            als_ops.resolve_solve_kernel(10, np.float32)


# ---------------------------------------------------------------------------
# K-Means single-shot padding (ISSUE 9 satellite)
# ---------------------------------------------------------------------------


class TestSingleShotPaddingJitted:
    def test_second_call_compiles_nothing(self, rng):
        """lloyd_accumulate_pallas pads INSIDE one jitted program now: a
        repeat call with the same signature must hit jit's executable
        cache — zero new XLA backend compiles (the old path re-dispatched
        ~6 eager padding ops per call that the cache could not see)."""
        from oap_mllib_tpu.ops.pallas.kmeans_kernel import (
            lloyd_accumulate_pallas,
        )

        n, d, k = 333, 5, 3
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        w = jnp.ones((n,), jnp.float32)
        c = jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))
        s1, c1, t1 = lloyd_accumulate_pallas(x, w, c, interpret=True)
        np.asarray(s1)
        before = progcache.xla_compile_count()
        s2, c2, t2 = lloyd_accumulate_pallas(x, w, c, interpret=True)
        np.asarray(s2)
        assert progcache.xla_compile_count() - before == 0
        assert np.array_equal(np.asarray(s1), np.asarray(s2))
