"""Pallas fused-kernel tests (interpret mode on the CPU pseudo-cluster).

Compiled-mode (non-interpret) coverage on real TPU hardware lives in
``tests_tpu/`` — run by dev/ci.sh whenever a TPU backend is present — so a
Mosaic lowering regression cannot ship green on the CPU suite alone.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from oap_mllib_tpu.ops.kmeans_ops import _accumulate, lloyd_run
from oap_mllib_tpu.ops.pallas.kmeans_kernel import (
    lloyd_accumulate_pallas,
    lloyd_run_pallas,
)


class TestFusedAccumulate:
    def test_matches_xla_accumulate(self, rng):
        n, d, k = 700, 20, 7
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        w = jnp.asarray((rng.random(n) < 0.9).astype(np.float32))
        c = jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))
        s1, c1, t1 = _accumulate(x, w, c)
        s2, c2, t2 = lloyd_accumulate_pallas(x, w, c, interpret=True)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), atol=1e-4)
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), atol=0)
        np.testing.assert_allclose(float(t1), float(t2), rtol=1e-5)

    def test_weighted_rows(self, rng):
        n, d, k = 600, 8, 3
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        w = jnp.asarray(rng.random(n).astype(np.float32))  # fractional weights
        c = jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))
        s1, c1, t1 = _accumulate(x, w, c)
        s2, c2, t2 = lloyd_accumulate_pallas(x, w, c, interpret=True)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), atol=1e-4)
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), atol=1e-5)

    @pytest.mark.parametrize("mode,sums_atol", [("high", 5e-3), ("default", 2e-1)])
    def test_fast_tiers_close(self, rng, mode, sums_atol):
        """bf16 tiers: "high" sums stay ~f32-exact via the hi/lo split (the
        one-hot is exactly representable); "default" is single-pass all
        -bf16 — the XLA default tier's ~1e-3-relative envelope.  Distances
        may flip near-ties only."""
        n, d, k = 640, 24, 9
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        w = jnp.asarray((rng.random(n) + 0.5).astype(np.float32))
        c = jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))
        s1, c1, t1 = _accumulate(x, w, c)
        s2, c2, t2 = lloyd_accumulate_pallas(x, w, c, mode=mode, interpret=True)
        # well-separated random clusters: assignments identical
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), atol=1e-3)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), atol=sums_atol)
        np.testing.assert_allclose(float(t1), float(t2), rtol=1e-3)

    def test_bad_mode_raises(self, rng):
        x = jnp.zeros((8, 4), jnp.float32)
        w = jnp.ones((8,), jnp.float32)
        c = jnp.zeros((2, 4), jnp.float32)
        with pytest.raises(ValueError, match="mode"):
            lloyd_accumulate_pallas(x, w, c, mode="fast", interpret=True)

    def test_unaligned_shapes_padded(self, rng):
        """n, k, d all unaligned to blocks/lanes: padding must be invisible."""
        n, d, k = 333, 5, 3
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        w = jnp.ones((n,), jnp.float32)
        c = jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))
        s1, c1, _ = _accumulate(x, w, c)
        s2, c2, _ = lloyd_accumulate_pallas(x, w, c, interpret=True)
        assert float(jnp.sum(c2)) == n  # no row lost to padding
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), atol=1e-4)


class TestFusedLloydLoop:
    def test_matches_xla_lloyd(self, rng):
        n, d, k = 640, 6, 4
        x = rng.normal(size=(n, d)).astype(np.float32)
        init = x[rng.choice(n, k, replace=False)]
        xj, wj = jnp.asarray(x), jnp.ones((n,), jnp.float32)
        cj = jnp.asarray(init)
        tol = jnp.asarray(1e-6, jnp.float32)
        c1, i1, t1, n1 = lloyd_run(xj, wj, cj, 25, tol)
        c2, i2, t2, n2 = lloyd_run_pallas(xj, wj, cj, 25, tol, interpret=True)
        assert int(i1) == int(i2)
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), atol=1e-3)
        np.testing.assert_allclose(float(t1), float(t2), rtol=1e-3)
        np.testing.assert_allclose(np.asarray(n1), np.asarray(n2), atol=1e-5)
