"""Compile-amortization subsystem: program-cache registry, shape
bucketing, persistent-cache wiring, and the cross-fit program-reuse
contract (ISSUE 2).

The reuse probes assert on REAL XLA backend compiles
(progcache.xla_compile_count, the jax monitoring event) — not just the
registry's own counters — so a regression that re-traces programs
cannot hide behind correct bookkeeping."""

import os

import numpy as np
import pytest

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.data.bucketing import bucket_factor, bucket_rows
from oap_mllib_tpu.utils import progcache
from oap_mllib_tpu.utils.progcache import ProgramCache
from oap_mllib_tpu.utils.timing import Timings


class TestRegistry:
    def test_get_or_build_caches_and_counts(self):
        pc = ProgramCache()
        built = []

        def build():
            built.append(1)
            return "prog"

        assert pc.get_or_build("algo", ("k",), build) == "prog"
        assert pc.get_or_build("algo", ("k",), build) == "prog"
        assert built == [1]
        s = pc.stats()
        assert s["hits"] == 1 and s["misses"] == 1
        assert s["by_algo"]["algo"] == {
            "hits": 1, "misses": 1, "evictions": 0,
        }

    def test_lru_eviction_counts(self):
        pc = ProgramCache(maxsize=2)
        for k in ("a", "b", "c"):
            pc.get_or_build("algo", (k,), lambda k=k: k)
        s = pc.stats()
        assert s["evictions"] == 1
        # "a" was evicted; rebuilding it is a miss again
        pc.get_or_build("algo", ("a",), lambda: "a2")
        assert pc.stats()["by_algo"]["algo"]["misses"] == 4

    def test_note_first_seen_then_hit(self):
        pc = ProgramCache()
        assert pc.note("x", (1,)) is True
        assert pc.note("x", (1,)) is False
        assert pc.note("x", (2,)) is True
        s = pc.stats()
        assert s["misses"] == 2 and s["hits"] == 1
        assert s["hit_rate"] == pytest.approx(1 / 3)

    def test_delta_is_per_fit(self):
        pc = ProgramCache()
        pc.note("x", (1,))
        before = pc.stats()
        pc.note("x", (1,))
        pc.note("x", (3,))
        # module-level delta() works off the module singleton; emulate
        # the arithmetic directly on this instance's snapshots
        now = pc.stats()
        d = {k: now[k] - before[k] for k in ("hits", "misses")}
        assert d == {"hits": 1, "misses": 1}

    def test_launch_books_compile_then_execute(self):
        t = Timings()
        with progcache.launch("t.algo", ("unique-key-1",), t, "phase"):
            pass
        with progcache.launch("t.algo", ("unique-key-1",), t, "phase"):
            pass
        sub = t.subphases("phase")
        assert "compile" in sub and "execute" in sub
        split = t.compile_split("phase")
        assert split is not None and split["compile"] >= 0.0

    def test_launch_record_execute_off_skips_hit_walls(self):
        t = Timings()
        for _ in range(3):
            with progcache.launch(
                "t.algo2", ("unique-key-2",), t, "phase",
                record_execute=False,
            ):
                pass
        sub = t.subphases("phase")
        assert "compile" in sub and "execute" not in sub

    def test_compile_split_none_without_launches(self):
        assert Timings().compile_split("phase") is None


class TestBucketing:
    def test_geometric_series(self):
        assert bucket_rows(1, 256) == 256
        assert bucket_rows(300, 256) == 512
        assert bucket_rows(512, 256) == 512
        assert bucket_rows(513, 256) == 1024
        assert bucket_rows(100) == 128
        assert bucket_rows(128) == 128

    def test_off_restores_exact_padding(self):
        set_config(shape_bucketing="off")
        assert bucket_rows(300, 256) == 512  # exact multiple of 256
        assert bucket_rows(700, 256) == 768  # NOT a power-of-two bucket
        assert bucket_rows(7) == 7

    def test_custom_factor(self):
        # gentler growth: buckets step ~1.25x instead of doubling
        assert bucket_rows(1000, 256, factor=1.25) == 1024
        assert bucket_rows(700, 256, factor=1.25) == 768

    def test_bad_values_raise(self):
        with pytest.raises(ValueError, match="shape_bucketing"):
            bucket_factor("bogus")
        with pytest.raises(ValueError, match="> 1"):
            bucket_factor("0.5")
        set_config(shape_bucketing="nope")
        with pytest.raises(ValueError, match="shape_bucketing"):
            bucket_rows(100, 256)

    def test_table_rows_land_on_buckets(self, rng):
        from oap_mllib_tpu.data.table import DenseTable
        from oap_mllib_tpu.parallel.mesh import get_mesh

        mesh = get_mesh()
        m0 = mesh.shape[mesh.axis_names[0]] * 256
        x = rng.normal(size=(2 * m0 + 100, 4)).astype(np.float32)
        t_on = DenseTable.from_numpy(x, mesh)
        assert t_on.n_padded == 4 * m0  # bucket, not the exact 3*m0
        assert t_on.n_rows == x.shape[0]
        np.testing.assert_array_equal(t_on.to_numpy(), x)
        assert float(np.asarray(t_on.mask)[x.shape[0]:].max(initial=0)) == 0

        set_config(shape_bucketing="off")
        t_off = DenseTable.from_numpy(x, mesh)
        assert t_off.n_padded == 3 * m0  # exact padding restored
        np.testing.assert_array_equal(t_off.to_numpy(), x)

    def test_chunk_rows_bucket(self, rng):
        from oap_mllib_tpu.data.stream import ChunkSource

        x = rng.normal(size=(250, 3))
        src = ChunkSource.from_array(x, chunk_rows=100)
        assert src.chunk_rows == 128
        np.testing.assert_allclose(
            np.concatenate([c[:v] for c, v in src]), x
        )
        set_config(shape_bucketing="off")
        assert ChunkSource.from_array(x, chunk_rows=100).chunk_rows == 100


@pytest.fixture
def jax_cache_restore():
    """Persistent-cache tests mutate process-global jax config; restore."""
    import jax

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_applied = progcache._persist_applied
    yield
    jax.config.update("jax_compilation_cache_dir", prev_dir)
    progcache._persist_applied = prev_applied


class TestPersistentCache:
    def test_dispatch_wires_cache_dir(self, tmp_path, jax_cache_restore):
        import jax

        from oap_mllib_tpu.utils.dispatch import should_accelerate

        cache_dir = str(tmp_path / "xla-cache")
        os.makedirs(cache_dir, exist_ok=True)
        set_config(compilation_cache_dir=cache_dir)
        assert should_accelerate("KMeans", True)
        assert jax.config.jax_compilation_cache_dir == cache_dir

    def test_fresh_program_persists_to_disk(self, tmp_path, rng,
                                            jax_cache_restore):
        """A fit with the cache dir set serializes its executables —
        the artifact a warm process reloads instead of recompiling."""
        from oap_mllib_tpu.models.kmeans import KMeans

        cache_dir = str(tmp_path / "xla-cache")
        os.makedirs(cache_dir, exist_ok=True)
        set_config(compilation_cache_dir=cache_dir)
        # a shape no other test uses, so the backend compile (and hence
        # the disk write) actually happens in this test
        x = rng.normal(size=(173, 9)).astype(np.float32)
        KMeans(k=3, seed=8, init_mode="random", max_iter=2).fit(x)
        assert len(os.listdir(cache_dir)) > 0


class TestCrossFitReuse:
    """The acceptance contract: the 2nd-through-Nth fit of any size in a
    bucket pays zero XLA compiles, and bucketing never changes results
    beyond fp summation order."""

    def _sizes(self):
        from oap_mllib_tpu.parallel.mesh import get_mesh

        mesh = get_mesh()
        m0 = mesh.shape[mesh.axis_names[0]] * 256
        # two sizes whose EXACT pads differ (3*m0 vs 4*m0) but whose x2
        # bucket (4*m0) is shared
        return 2 * m0 + 404, 3 * m0 + 37

    def test_kmeans_second_size_reuses_program(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        n1, n2 = self._sizes()
        x = rng.normal(size=(n2, 4)).astype(np.float32)

        def fit(n):
            return KMeans(
                k=4, seed=6, init_mode="random", max_iter=3
            ).fit(x[:n])

        m1 = fit(n1)
        assert m1.summary.accelerated
        before = progcache.xla_compile_count()
        m2 = fit(n2)
        assert m2.summary.accelerated
        assert progcache.xla_compile_count() - before == 0
        assert m2.summary.progcache["misses"] == 0
        assert m2.summary.progcache["hits"] > 0

    def test_kmeans_extra_masked_row_identical(self, rng):
        """Fitting n vs n+1 rows (same data + one extra weight-0 row)
        lands in one bucket and yields identical centers — the padding
        contract, exercised through the real table layer."""
        import jax.numpy as jnp

        from oap_mllib_tpu.data.table import DenseTable
        from oap_mllib_tpu.ops import kmeans_ops
        from oap_mllib_tpu.parallel.mesh import get_mesh

        n1, _ = self._sizes()
        mesh = get_mesh()
        x = rng.normal(size=(n1 + 1, 5)).astype(np.float32)
        init = x[rng.choice(n1, 4, replace=False)]
        t1 = DenseTable.from_numpy(x[:n1], mesh)
        t2 = DenseTable.from_numpy(x, mesh)
        assert t1.n_padded == t2.n_padded  # same bucket -> same program
        w2 = np.asarray(t2.mask).copy()
        w2[n1] = 0.0  # mask the extra point out
        r1 = kmeans_ops.lloyd_run(
            t1.data, t1.mask, jnp.asarray(init), 5,
            jnp.asarray(1e-6, jnp.float32),
        )
        r2 = kmeans_ops.lloyd_run(
            t2.data, jnp.asarray(w2), jnp.asarray(init), 5,
            jnp.asarray(1e-6, jnp.float32),
        )
        np.testing.assert_allclose(
            np.asarray(r1[0]), np.asarray(r2[0]), atol=1e-6
        )
        assert int(r1[1]) == int(r2[1])

    def test_kmeans_bucketing_parity_on_vs_off(self, rng):
        from oap_mllib_tpu.models.kmeans import KMeans

        n1, _ = self._sizes()
        x = rng.normal(size=(n1, 4)).astype(np.float32)
        m_on = KMeans(k=4, seed=6, init_mode="random", max_iter=4).fit(x)
        set_config(shape_bucketing="off")
        m_off = KMeans(k=4, seed=6, init_mode="random", max_iter=4).fit(x)
        np.testing.assert_allclose(
            m_on.cluster_centers_, m_off.cluster_centers_, atol=1e-6
        )

    def test_pca_second_size_reuses_program(self, rng):
        from oap_mllib_tpu.models.pca import PCA

        n1, n2 = self._sizes()
        x = rng.normal(size=(n2, 6)).astype(np.float32)
        p1 = PCA(k=3).fit(x[:n1])
        assert p1.summary["accelerated"]
        before = progcache.xla_compile_count()
        p2 = PCA(k=3).fit(x)
        assert p2.summary["accelerated"]
        assert progcache.xla_compile_count() - before == 0
        assert p2.summary["progcache"]["misses"] == 0

    def test_pca_bucketing_parity_on_vs_off(self, rng):
        import jax.numpy as jnp

        from oap_mllib_tpu.data.table import DenseTable
        from oap_mllib_tpu.ops import pca_ops
        from oap_mllib_tpu.parallel.mesh import get_mesh

        n1, _ = self._sizes()
        mesh = get_mesh()
        x = rng.normal(size=(n1, 6)).astype(np.float32) + 3.0
        covs = []
        for mode in ("on", "off"):
            set_config(shape_bucketing=mode)
            t = DenseTable.from_numpy(x, mesh)
            cov, mean = pca_ops.covariance(
                t.data, t.mask, jnp.asarray(float(t.n_rows), jnp.float32)
            )
            covs.append((np.asarray(cov), np.asarray(mean)))
        np.testing.assert_allclose(covs[0][0], covs[1][0], atol=1e-5)
        np.testing.assert_allclose(covs[0][1], covs[1][1], atol=1e-6)

    def test_als_extra_zero_rating_reuses_and_matches(self, rng):
        """The ALS leg: one extra implicit rating of 0 (contributes
        exactly nothing: A-weight alpha*|0|, b only for r > 0) lands in
        the grouped layout's padding slack — same shapes, same program,
        identical factors."""
        from oap_mllib_tpu.models.als import ALS

        n_users, n_items = 30, 20
        users = np.repeat(np.arange(n_users), 10)
        items = np.concatenate(
            [(np.arange(10) + j) % n_items for j in range(n_users)]
        )
        ratings = (rng.random(len(users)) * 4 + 1).astype(np.float32)

        def fit(u, i, r):
            # num_user_blocks=1 pins the single-device grouped path (the
            # 8-rank block path's per-rank group maxima legitimately
            # shift with the edge distribution)
            return ALS(
                rank=4, max_iter=2, reg_param=0.1, alpha=10.0,
                implicit_prefs=True, seed=3, num_user_blocks=1,
            ).fit(u, i, r, n_users=n_users, n_items=n_items)

        m1 = fit(users, items, ratings)
        assert m1.summary["accelerated"]
        assert m1.summary["als_kernel"] == "grouped"
        before = progcache.xla_compile_count()
        m2 = fit(
            np.append(users, 0),
            np.append(items, 17),
            np.append(ratings, np.float32(0.0)),
        )
        assert progcache.xla_compile_count() - before == 0
        assert m2.summary["progcache"]["misses"] == 0
        np.testing.assert_allclose(
            m1.user_factors_, m2.user_factors_, atol=1e-7
        )
        np.testing.assert_allclose(
            m1.item_factors_, m2.item_factors_, atol=1e-7
        )
