"""Compiled-mode TPU tests for ALS: grouped-edge vs COO parity on hardware.

tests/test_als.py proves both program families against the NumPy oracle on
the CPU pseudo-cluster; this suite compiles them for the real chip and
holds them to each other — the grouped-edge path's batched (r+1, r+2) MXU
matmuls and the COO path's segment-sum scatters take different XLA-TPU
lowering routes, so a precision or Mosaic regression in either shows up
here first.  Both feedback modes are covered (the reference accelerates
implicit only, ALS.scala:925; we accelerate both).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from oap_mllib_tpu.ops import als_ops


def _synthetic(rng, n_users=512, n_items=256, nnz=8192):
    u = rng.integers(0, n_users, size=nnz).astype(np.int32)
    i = rng.integers(0, n_items, size=nnz).astype(np.int32)
    r = (rng.random(nnz) * 4 + 1).astype(np.float32)
    return u, i, r


class TestGroupedVsCooCompiled:
    @pytest.mark.parametrize("implicit", [True, False])
    def test_full_loop_parity(self, rng, implicit):
        n_users, n_items, rank, iters = 512, 256, 8, 3
        u, i, r = _synthetic(rng, n_users, n_items)
        x0 = (rng.normal(size=(n_users, rank)) * 0.1).astype(np.float32)
        y0 = (rng.normal(size=(n_items, rank)) * 0.1).astype(np.float32)
        valid = jnp.ones((len(u),), jnp.float32)
        reg, alpha = 0.1, 10.0

        by_user = als_ops.build_grouped_edges(u, i, r, n_users)
        by_item = als_ops.build_grouped_edges(i, u, r, n_items)
        xg, yg = als_ops.als_run_grouped(
            *[jnp.asarray(a) for a in by_user],
            *[jnp.asarray(a) for a in by_item],
            jnp.asarray(x0), jnp.asarray(y0),
            n_users, n_items, iters, reg, alpha, implicit,
        )
        if implicit:
            xc, yc = als_ops.als_implicit_run(
                jnp.asarray(u), jnp.asarray(i), jnp.asarray(r), valid,
                jnp.asarray(x0), jnp.asarray(y0),
                n_users, n_items, iters, reg, alpha,
            )
        else:
            xc, yc = als_ops.als_explicit_run(
                jnp.asarray(u), jnp.asarray(i), jnp.asarray(r), valid,
                jnp.asarray(x0), jnp.asarray(y0),
                n_users, n_items, iters, reg,
            )
        np.testing.assert_allclose(np.asarray(xg), np.asarray(xc), atol=2e-3)
        np.testing.assert_allclose(np.asarray(yg), np.asarray(yc), atol=2e-3)

    def test_partials_parity(self, rng):
        """One half-iteration's (A, b, n_reg) partials: grouped == COO."""
        n_users, n_items, rank = 300, 200, 10
        u, i, r = _synthetic(rng, n_users, n_items, nnz=4096)
        y = rng.normal(size=(n_items, rank)).astype(np.float32)
        valid = jnp.ones((len(u),), jnp.float32)
        a1, b1, n1 = als_ops.normal_eq_partials(
            jnp.asarray(u), jnp.asarray(i), jnp.asarray(r), valid,
            jnp.asarray(y), n_users, 40.0, True,
        )
        src_g, conf_g, valid_g, group_dst = als_ops.build_grouped_edges(
            u, i, r, n_users
        )
        a2, b2, n2 = als_ops.normal_eq_partials_grouped(
            jnp.asarray(src_g), jnp.asarray(conf_g), jnp.asarray(valid_g),
            jnp.asarray(group_dst), jnp.asarray(y), n_users, 40.0, True,
        )
        np.testing.assert_allclose(np.asarray(a1), np.asarray(a2), rtol=2e-4,
                                   atol=2e-2)
        np.testing.assert_allclose(np.asarray(b1), np.asarray(b2), rtol=2e-4,
                                   atol=2e-2)
        np.testing.assert_allclose(np.asarray(n1), np.asarray(n2), atol=1e-3)


class TestEstimatorCompiled:
    @pytest.mark.parametrize("implicit", [True, False])
    def test_fit_improves_rmse(self, rng, implicit):
        """ALS().fit end-to-end on the session backend: reconstruction
        improves over the init and the accelerated path was taken."""
        from oap_mllib_tpu.models.als import ALS

        n_users, n_items = 400, 300
        # planted low-rank structure so ALS has signal to recover
        xt = rng.normal(size=(n_users, 6)).astype(np.float32)
        yt = rng.normal(size=(n_items, 6)).astype(np.float32)
        u, i, _ = _synthetic(rng, n_users, n_items, nnz=6000)
        r = np.abs(np.sum(xt[u] * yt[i], axis=1)) + 0.1
        m = ALS(rank=6, max_iter=8, reg_param=0.05, alpha=40.0,
                implicit_prefs=implicit, seed=7).fit(u, i, r)
        assert m.summary["accelerated"]
        pred = m.predict(u, i)
        if implicit:
            # implicit predicts preference: observed pairs must score well
            # above random pairs (the model's actual ranking semantics —
            # absolute closeness to 1 depends on reg/alpha shrinkage)
            ru = rng.integers(0, n_users, size=len(u)).astype(np.int32)
            ri = rng.integers(0, n_items, size=len(u)).astype(np.int32)
            rand_pred = m.predict(ru, ri)
            assert float(pred.mean()) > float(rand_pred.mean()) + 0.2
        else:
            rmse = float(np.sqrt(np.mean((pred - r) ** 2)))
            assert rmse < 0.5 * float(np.std(r))

    def test_recommend_scores_match_predict_on_hardware(self, rng):
        """The recommend matmul must run at HIGHEST precision: TPU's
        default bf16 matmul drifts the returned scores ~1e-3 off
        predict() and can swap near-tie rankings — invisible to the CPU
        suite (f32 matmuls there), caught only on hardware (round 5)."""
        from oap_mllib_tpu.models.als import ALS

        u, i, _ = _synthetic(rng, 80, 60, nnz=2500)
        r = (rng.random(len(u)) * 4 + 1).astype(np.float32)
        m = ALS(rank=4, max_iter=2, implicit_prefs=True, seed=1).fit(u, i, r)
        ids, scores = m.recommend_for_all_users(5, with_scores=True)
        uu = np.repeat(np.arange(ids.shape[0]), 5)
        np.testing.assert_allclose(
            scores.ravel(), m.predict(uu, ids.ravel()), atol=1e-5
        )
        sub = np.array([7, 3, 7])
        sids, sscores = m.recommend_for_users(sub, 5, with_scores=True)
        full = m.user_factors_[sub] @ m.item_factors_.T
        np.testing.assert_allclose(
            np.take_along_axis(full, sids, axis=1), sscores, atol=1e-5
        )


class TestGroupedChunkedCompiled:
    def test_chunked_scan_path_compiled(self, rng, monkeypatch):
        """The G-blocked lax.scan partials (the ML-25M-on-one-chip path)
        compile for the real chip and match the unchunked program — the
        flat (n_dst, (r+1)(r+2)) carry and the padded dummy groups take
        lowering routes the interpret-mode CPU test cannot validate."""
        n_users, n_items, rank, iters = 512, 256, 8, 2
        u, i, r = _synthetic(rng, n_users, n_items)
        x0 = (rng.normal(size=(n_users, rank)) * 0.1).astype(np.float32)
        y0 = (rng.normal(size=(n_items, rank)) * 0.1).astype(np.float32)
        by_user = als_ops.build_grouped_edges(u, i, r, n_users)
        by_item = als_ops.build_grouped_edges(i, u, r, n_items)
        dev = [jnp.asarray(a) for a in (*by_user, *by_item)]

        def run():
            return als_ops.als_run_grouped(
                *dev, jnp.asarray(x0), jnp.asarray(y0),
                n_users, n_items, iters, 0.1, 10.0, True,
            )

        x1, y1 = run()
        # force the scan path: budget far below this side's (G, P, r) size
        # (odd split so the dummy-group padding lowers on hardware too)
        monkeypatch.setattr(als_ops, "_GROUPED_BUDGET_ELEMS", 1 << 14)
        assert als_ops._grouped_block_count(*by_user[0].shape, rank) > 1
        als_ops._als_run_grouped_jit.clear_cache()
        x2, y2 = run()
        np.testing.assert_allclose(np.asarray(x1), np.asarray(x2), atol=2e-4)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), atol=2e-4)
        # monkeypatch teardown restores the budget; clearing the jit cache
        # keeps the small-budget trace from leaking into later tests
        als_ops._als_run_grouped_jit.clear_cache()


class TestStreamedALSTpu:
    def test_streamed_matches_in_memory_compiled(self, rng):
        """The host-chunked streamed ALS (ops/als_stream.py) on the real
        chip: per-chunk moment accumulation + flat-carry solve must match
        the one-program in-memory grouped run (compiled lowerings of the
        donated-carry segment-sum differ from the CPU suite's)."""
        import jax.numpy as jnp

        from oap_mllib_tpu.ops import als_ops, als_stream

        n_users, n_items, nnz, rank, iters = 300, 200, 20_000, 6, 3
        u = rng.integers(0, n_users, nnz).astype(np.int64)
        i = rng.integers(0, n_items, nnz).astype(np.int64)
        r = (rng.random(nnz) * 4 + 1).astype(np.float32)
        x0 = (rng.normal(size=(n_users, rank)) * 0.1).astype(np.float32)
        y0 = (rng.normal(size=(n_items, rank)) * 0.1).astype(np.float32)
        by_user = als_ops.build_grouped_edges(u, i, r, n_users)
        by_item = als_ops.build_grouped_edges(i, u, r, n_items)
        dev = [jnp.asarray(a) for a in (*by_user, *by_item)]
        xm, ym = als_ops.als_run_grouped(
            *dev, jnp.asarray(x0), jnp.asarray(y0),
            n_users, n_items, iters, 0.1, 5.0, True,
        )
        xs, ys = als_stream.als_run_streamed(
            by_user, by_item, x0, y0, n_users, n_items, iters, 0.1, 5.0,
            True,
        )
        np.testing.assert_allclose(np.asarray(xm), xs, atol=2e-4)
        np.testing.assert_allclose(np.asarray(ym), ys, atol=2e-4)
