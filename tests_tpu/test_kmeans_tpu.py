"""Compiled-mode TPU tests: Mosaic-lowered Pallas kernels + precision tiers.

Round-1 gap (VERDICT weak #2): every Pallas assertion ran interpret-only, so
a Mosaic lowering regression would ship green.  These tests compile the
fused kernel for the real chip and hold it to the XLA path's results, and
pin the "high" (bf16_3x) tier inside the 1e-4 parity envelope.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oap_mllib_tpu.ops.kmeans_ops import _accumulate, lloyd_run
from oap_mllib_tpu.ops.pallas.kmeans_kernel import (
    lloyd_accumulate_pallas,
    lloyd_run_pallas,
)


class TestPallasCompiled:
    def test_accumulate_compiled_matches_xla(self, rng):
        n, d, k = 4096, 100, 37
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        w = jnp.asarray((rng.random(n) + 0.5).astype(np.float32))
        c = jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))
        s1, c1, t1 = _accumulate(x, w, c)
        s2, c2, t2 = lloyd_accumulate_pallas(x, w, c)  # interpret=False
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), atol=1e-3)
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), atol=1e-3)
        np.testing.assert_allclose(float(t1), float(t2), rtol=1e-5)

    def test_lloyd_loop_compiled(self, rng):
        n, d, k = 8192, 32, 16
        x = rng.normal(size=(n, d)).astype(np.float32)
        init = x[rng.choice(n, k, replace=False)]
        xj, wj = jnp.asarray(x), jnp.ones((n,), jnp.float32)
        cj = jnp.asarray(init)
        tol = jnp.asarray(1e-6, jnp.float32)
        c1, i1, t1, _ = lloyd_run(xj, wj, cj, 20, tol)
        c2, i2, t2, _ = lloyd_run_pallas(xj, wj, cj, 20, tol)
        assert int(i1) == int(i2)
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), atol=1e-3)
        np.testing.assert_allclose(float(t1), float(t2), rtol=1e-3)

    @pytest.mark.parametrize("mode,bound", [("high", 1e-4), ("default", 5e-3)])
    def test_fast_tiers_compiled_within_parity(self, rng, mode, bound):
        """Fast tiers on blob-like data: "high" centers within the 1e-4
        parity bar; "default" (single-pass all-bf16 sums) within the XLA
        default tier's ~1e-3-relative envelope."""
        n, d, k = 16384, 64, 32
        proto = rng.normal(size=(k, d)).astype(np.float32)
        x = proto[rng.integers(k, size=n)] + 0.1 * rng.normal(size=(n, d)).astype(
            np.float32
        )
        init = proto + 0.01 * rng.normal(size=(k, d)).astype(np.float32)
        xj, wj = jnp.asarray(x), jnp.ones((n,), jnp.float32)
        cj = jnp.asarray(init)
        tol = jnp.asarray(0.0, jnp.float32)
        c1, _, t1, _ = lloyd_run(xj, wj, cj, 5, tol)
        c2, _, t2, _ = lloyd_run_pallas(xj, wj, cj, 5, tol, mode=mode)
        scale = float(jnp.max(jnp.abs(c1)))
        assert float(jnp.max(jnp.abs(c1 - c2))) / scale < bound
        assert abs(float(t1) - float(t2)) / float(t1) < bound


class TestXlaPrecisionTiers:
    def test_high_tier_within_parity(self, rng):
        """XLA "high" (bf16_3x) vs "highest" on blob data: 1e-4 envelope
        (round-1 measured 6.6e-5 cost error at bench scale)."""
        n, d, k = 16384, 64, 32
        proto = rng.normal(size=(k, d)).astype(np.float32)
        x = proto[rng.integers(k, size=n)] + 0.1 * rng.normal(size=(n, d)).astype(
            np.float32
        )
        init = proto + 0.01 * rng.normal(size=(k, d)).astype(np.float32)
        xj, wj = jnp.asarray(x), jnp.ones((n,), jnp.float32)
        cj = jnp.asarray(init)
        tol = jnp.asarray(0.0, jnp.float32)
        c1, _, t1, _ = lloyd_run(xj, wj, cj, 5, tol, 1, "highest")
        c2, _, t2, _ = lloyd_run(xj, wj, cj, 5, tol, 1, "high")
        scale = float(jnp.max(jnp.abs(c1)))
        assert float(jnp.max(jnp.abs(c1 - c2))) / scale < 1e-4
        assert abs(float(t1) - float(t2)) / float(t1) < 1e-4

    def test_auto_picks_pallas_for_deep_features(self, rng, monkeypatch):
        """kmeans_kernel=auto routes the f32-accurate tiers to the fused
        kernel (BASELINE.md kernel-table rule: pallas wins every profiled
        shape at highest/high) — verified by counting calls, not
        inferred."""
        if len(jax.devices()) != 1:
            pytest.skip("pallas estimator path requires a single device")
        import oap_mllib_tpu.ops.pallas.kmeans_kernel as pk
        from oap_mllib_tpu.config import set_config
        from oap_mllib_tpu.models.kmeans import KMeans

        calls = []
        real = pk.lloyd_run_pallas
        monkeypatch.setattr(
            pk, "lloyd_run_pallas",
            lambda *a, **kw: (calls.append(1), real(*a, **kw))[1],
        )
        set_config(kmeans_kernel="auto", matmul_precision="high")
        try:
            x = rng.normal(size=(2048, 256)).astype(np.float32)
            m = KMeans(k=8, max_iter=5, seed=1).fit(x)
            assert m.summary.accelerated
            assert calls, "auto did not pick pallas for d=256 at high tier"
        finally:
            set_config(matmul_precision="highest")

    def test_estimator_pallas_kernel_config(self, rng, monkeypatch):
        """KMeans(kmeans_kernel=pallas) runs the fused kernel end-to-end —
        verified by counting calls into the pallas module, not inferred."""
        if len(jax.devices()) != 1:
            pytest.skip("pallas estimator path requires a single device")
        import oap_mllib_tpu.ops.pallas.kmeans_kernel as pk
        from oap_mllib_tpu.config import set_config
        from oap_mllib_tpu.models.kmeans import KMeans

        calls = []
        real = pk.lloyd_run_pallas
        monkeypatch.setattr(
            pk, "lloyd_run_pallas",
            lambda *a, **kw: (calls.append(1), real(*a, **kw))[1],
        )
        set_config(kmeans_kernel="pallas")
        try:
            x = rng.normal(size=(2048, 16)).astype(np.float32)
            m = KMeans(k=4, max_iter=10, seed=1).fit(x)
            assert m.summary.accelerated
            assert calls, "pallas kernel was configured but never invoked"
            # auto at the "default" tier routes to XLA (kernel-table rule:
            # XLA's all-bf16 pipeline wins that tier) — no new pallas call
            n_before = len(calls)
            set_config(kmeans_kernel="auto", matmul_precision="default")
            m2 = KMeans(k=4, max_iter=10, seed=1).fit(x)
            assert len(calls) == n_before
            np.testing.assert_allclose(
                m.summary.training_cost, m2.summary.training_cost, rtol=1e-2
            )
        finally:
            set_config(kmeans_kernel="auto", matmul_precision="highest")
