"""Real-hardware suite: compiled (non-interpret) kernels on an actual TPU.

Unlike ``tests/`` (which pins JAX to the 8-device virtual CPU pseudo-cluster),
this suite uses whatever backend the session has.  Every test is skipped
unless that backend is a TPU — dev/ci.sh invokes it only when one is present,
so a Mosaic lowering or precision regression cannot ship green.
"""

import numpy as np
import pytest


def pytest_collection_modifyitems(config, items):
    import jax

    if jax.default_backend() != "tpu":
        skip = pytest.mark.skip(reason="requires a real TPU backend")
        for item in items:
            item.add_marker(skip)


@pytest.fixture
def rng():
    return np.random.default_rng(42)
