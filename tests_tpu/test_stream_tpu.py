"""Compiled-mode streamed-fit tests: the out-of-core paths on a real TPU.

The CPU suite (tests/test_stream.py) proves the math; this records that
the per-chunk programs (donated accumulators, half-score loop mode,
streamed covariance) compile and agree with the in-memory device paths on
actual hardware.
"""

import numpy as np

from oap_mllib_tpu import KMeans, PCA
from oap_mllib_tpu.data.stream import ChunkSource


class TestStreamedCompiled:
    def test_kmeans_streamed_matches_in_memory(self, rng):
        k, d, n = 8, 64, 1 << 15
        protos = rng.normal(size=(k, d)).astype(np.float32) * 6.0
        x = (protos[rng.integers(k, size=n)]
             + rng.normal(size=(n, d)).astype(np.float32) * 0.1)
        src = ChunkSource.from_array(x, chunk_rows=1 << 13)
        m1 = KMeans(k=k, max_iter=15, seed=3).fit(src)
        m2 = KMeans(k=k, max_iter=15, seed=3).fit(x)
        assert getattr(m1.summary, "streamed", False)
        # blob recovery on both paths; costs agree (RNG-sensitive init:
        # cost-based compare, survey §7.3)
        for p in protos:
            assert np.min(
                np.linalg.norm(m1.cluster_centers_ - p, axis=1)
            ) < 0.5
        np.testing.assert_allclose(
            m1.summary.training_cost, m2.summary.training_cost, rtol=1e-2
        )

    def test_pca_streamed_matches_in_memory(self, rng):
        x = (rng.normal(size=(1 << 14, 32)) * rng.gamma(2.0, size=32)
             + 4.0).astype(np.float32)
        src = ChunkSource.from_array(x, chunk_rows=1 << 12)
        m1 = PCA(k=6).fit(src)
        m2 = PCA(k=6).fit(x)
        assert m1.summary["streamed"]
        np.testing.assert_allclose(
            np.abs(m1.components_), np.abs(m2.components_), atol=1e-3
        )
        np.testing.assert_allclose(
            m1.explained_variance_, m2.explained_variance_, atol=1e-5
        )
