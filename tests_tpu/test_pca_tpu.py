"""Compiled-mode TPU tests for PCA: the f32-HIGHEST Gram on real hardware.

The CPU pseudo-cluster suite (tests/test_pca.py) proves the math; this
suite proves the COMPILED program on the actual chip holds the same parity
bar — a Mosaic/XLA-TPU precision regression (e.g. a pass demoting the
HIGHEST-precision Gram to bf16) would ship green without it.  Oracle is
NumPy float64, compare style mirrors the reference's IntelPCASuite
(absTol + sign-insensitive eigenvector columns, IntelPCASuite.scala:39-88).
"""

import numpy as np

import jax.numpy as jnp

from oap_mllib_tpu.ops.pca_ops import covariance, eigh_descending, project


def _np_oracle(x64):
    n = x64.shape[0]
    mean = x64.mean(axis=0)
    xc = x64 - mean
    cov = xc.T @ xc / (n - 1)
    vals, vecs = np.linalg.eigh(cov)
    return cov, mean, vals[::-1], vecs[:, ::-1]


class TestPcaCompiled:
    def test_covariance_matches_f64_oracle(self, rng):
        n, d = 8192, 128
        x = rng.normal(size=(n, d)).astype(np.float32)
        cov_o, mean_o, _, _ = _np_oracle(x.astype(np.float64))
        cov, mean = covariance(
            jnp.asarray(x), jnp.ones((n,), jnp.float32),
            jnp.asarray(float(n), jnp.float32),
        )
        np.testing.assert_allclose(np.asarray(mean), mean_o, atol=1e-4)
        np.testing.assert_allclose(np.asarray(cov), cov_o, atol=1e-4)

    def test_eigh_components_sign_insensitive(self, rng):
        """Top components vs the f64 oracle, |.| compare per column and only
        where explained variance is material (the reference's compare rule,
        IntelPCASuite.scala:80-84)."""
        n, d, k = 4096, 64, 8
        # anisotropic data so the top-k spectrum is well separated
        scales = np.linspace(4.0, 0.5, d).astype(np.float32)
        x = (rng.normal(size=(n, d)) * scales).astype(np.float32)
        _, _, vals_o, vecs_o = _np_oracle(x.astype(np.float64))
        cov, _ = covariance(
            jnp.asarray(x), jnp.ones((n,), jnp.float32),
            jnp.asarray(float(n), jnp.float32),
        )
        vals, vecs = eigh_descending(cov)
        vals, vecs = np.asarray(vals), np.asarray(vecs)
        ratio_o = vals_o / vals_o.sum()
        np.testing.assert_allclose(vals[:k], vals_o[:k], rtol=1e-3)
        for j in range(k):
            if ratio_o[j] > 1e-5:
                np.testing.assert_allclose(
                    np.abs(vecs[:, j]), np.abs(vecs_o[:, j]), atol=1e-3
                )

    def test_precision_tiers_large_mean(self, rng):
        """Per-tier covariance error vs the f64 oracle on LARGE-MEAN data
        (mean=50, unit variance) — the case that killed the one-pass
        raw-moment form (4.6e-3 at f32-HIGHEST via the gram ~ n*mu*mu^T
        cancellation; v5e, round 3).  The centered two-pass form must hold
        every tier to its documented bound."""
        n, d = 16384, 256
        x = (rng.normal(size=(n, d)) + 50.0).astype(np.float32)
        cov_o, _, _, _ = _np_oracle(x.astype(np.float64))
        scale = np.max(np.abs(cov_o))
        ones = jnp.ones((n,), jnp.float32)
        nr = jnp.asarray(float(n), jnp.float32)
        bounds = {"highest": 1e-5, "high": 1e-4, "default": 1e-3}
        for tier, bound in bounds.items():
            cov, _ = covariance(jnp.asarray(x), ones, nr, tier)
            err = float(np.max(np.abs(np.asarray(cov) - cov_o))) / scale
            assert err < bound, (tier, err)

    def test_project_matches_oracle(self, rng):
        n, d, k = 2048, 32, 4
        x = rng.normal(size=(n, d)).astype(np.float32)
        comps = rng.normal(size=(d, k)).astype(np.float32)
        out = project(jnp.asarray(x), jnp.asarray(comps))
        np.testing.assert_allclose(
            np.asarray(out), x.astype(np.float64) @ comps.astype(np.float64),
            atol=1e-3,
        )

    def test_estimator_end_to_end(self, rng):
        """PCA().fit on the session backend: explained-variance ratios match
        the f64 oracle and transform round-trips."""
        from oap_mllib_tpu.models.pca import PCA

        n, d, k = 4096, 48, 6
        scales = np.linspace(3.0, 0.25, d).astype(np.float32)
        x = (rng.normal(size=(n, d)) * scales).astype(np.float32)
        _, _, vals_o, _ = _np_oracle(x.astype(np.float64))
        m = PCA(k=k).fit(x)
        assert m.summary["accelerated"]
        np.testing.assert_allclose(
            m.explained_variance_, vals_o[:k] / vals_o.sum(), atol=1e-4
        )
        assert m.transform(x[:16]).shape == (16, k)

    def test_randomized_solver_compiled(self, rng):
        """pca_solver="randomized" on the real chip: the QR + subspace
        iteration lowering must match eigh on a decaying spectrum (the
        solver's advertised regime) — hardware QR/eigh lowerings differ
        from the CPU suite's."""
        from oap_mllib_tpu.config import set_config
        from oap_mllib_tpu.models.pca import PCA

        n, d, k = 4096, 64, 5
        scales = (2.0 ** -np.arange(d)).astype(np.float32)
        basis = np.linalg.qr(rng.normal(size=(d, d)))[0].astype(np.float32)
        x = ((rng.normal(size=(n, d)).astype(np.float32) * scales * 10)
             @ basis.T)
        m_eigh = PCA(k=k).fit(x)
        set_config(pca_solver="randomized")
        try:
            m_rand = PCA(k=k).fit(x)
        finally:
            set_config(pca_solver="auto")
        np.testing.assert_allclose(
            m_rand.explained_variance_, m_eigh.explained_variance_,
            rtol=1e-3, atol=1e-6,
        )
        dots = np.abs(np.einsum(
            "dk,dk->k", m_rand.components_, m_eigh.components_
        ))
        assert np.all(dots > 1.0 - 1e-3), dots
