"""Compiled-mode TPU legs for the ISSUE 9 kernel plane: Mosaic-lowered
PCA moments + ALS solve parity, the remote-DMA ring kernel vs the psum
reference on the real mesh, and the ring's overlap-efficiency bound.

Skipped (whole module) unless the session backend is a TPU — see
conftest.py; dev/ci.sh runs this suite whenever one is present.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from oap_mllib_tpu.ops import als_ops
from oap_mllib_tpu.ops.pallas.als_kernel import solve_normal_eq_pallas
from oap_mllib_tpu.ops.pallas.pca_kernel import covariance_pallas
from oap_mllib_tpu.ops.pallas.ring_reduce import ring_allreduce
from oap_mllib_tpu.ops.pca_ops import _covariance_jit
from oap_mllib_tpu.utils.jax_compat import shard_map


class TestPcaKernelCompiled:
    def test_covariance_compiled_matches_xla(self, rng):
        n, d = 4096, 96
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32) + 3.0)
        m = jnp.asarray((rng.random(n) < 0.9).astype(np.float32))
        nv = jnp.asarray(float(np.asarray(m).sum()))
        cov_p, mean_p = covariance_pallas(x, m, nv)  # interpret=False
        cov_r, mean_r = _covariance_jit(x, m, nv)
        np.testing.assert_allclose(
            np.asarray(mean_p), np.asarray(mean_r), atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(cov_p), np.asarray(cov_r), atol=1e-4
        )

    @pytest.mark.parametrize("mode,atol", [("high", 1e-3), ("default", 5e-2)])
    def test_split_tiers_compiled(self, rng, mode, atol):
        n, d = 2048, 64
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        m = jnp.ones((n,), jnp.float32)
        nv = jnp.asarray(float(n))
        cov_t, _ = covariance_pallas(x, m, nv, mode=mode)
        cov_r, _ = _covariance_jit(x, m, nv)
        np.testing.assert_allclose(
            np.asarray(cov_t), np.asarray(cov_r), atol=atol
        )


class TestAlsSolveCompiled:
    def test_solve_compiled_matches_xla(self, rng):
        n, r = 4096, 10
        m = rng.normal(size=(n, r, r)).astype(np.float32)
        a = jnp.asarray(
            np.einsum("nij,nkj->nik", m, m) + 0.5 * np.eye(r)
        )
        b = jnp.asarray(rng.normal(size=(n, r)).astype(np.float32))
        n_reg = jnp.asarray(rng.integers(0, 40, n).astype(np.float32))
        g = rng.normal(size=(64, r)).astype(np.float32)
        gram = jnp.asarray(g.T @ g * 0.01)
        eye = jnp.eye(r, dtype=jnp.float32)
        ref = als_ops.regularized_solve(a, b, n_reg, 0.1, eye, gram)
        out = solve_normal_eq_pallas(a, b, n_reg, 0.1, gram)
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(out), atol=1e-4
        )


@pytest.fixture
def ring_mesh():
    n = len(jax.devices())
    if n < 2:
        pytest.skip("ring kernel needs >= 2 TPU devices")
    return jax.make_mesh((n,), ("data",)), n


class TestRingCompiled:
    def _run(self, mesh, world, g, interpret=False):
        gd = jax.device_put(
            jnp.asarray(g), NamedSharding(mesh, P("data", None, None))
        )
        fn = jax.jit(
            shard_map(
                lambda b: ring_allreduce(
                    b[0], "data", world, interpret=interpret
                )[None],
                mesh=mesh, in_specs=P("data", None, None),
                out_specs=P("data", None, None), check_vma=False,
            )
        )
        return np.asarray(fn(gd))

    def test_remote_dma_ring_matches_psum_reference(self, rng, ring_mesh):
        """The acceptance bound on hardware: the Mosaic remote-DMA ring
        vs the ppermute parity schedule (identical segment order) and
        the plain sum, at 1e-5."""
        mesh, world = ring_mesh
        g = rng.normal(size=(world, 1000, 384)).astype(np.float32)
        out_dma = self._run(mesh, world, g, interpret=False)
        out_ref = self._run(mesh, world, g, interpret=True)  # ppermute
        scale = np.abs(g.sum(0)).max()
        np.testing.assert_allclose(
            out_dma[0], g.sum(0), rtol=1e-5, atol=1e-5 * scale
        )
        # same schedule -> bit-identical across the two backends
        np.testing.assert_allclose(
            out_dma[0], out_ref[0], rtol=1e-6, atol=1e-6 * scale
        )
        for i in range(1, world):
            assert np.array_equal(out_dma[0], out_dma[i])

    def test_ring_overlap_efficiency(self, rng, ring_mesh):
        """Overlap-efficiency leg: the ring-fused model-sharded Lloyd
        pass must not be slower than the psum path (the bi-directional
        DMA ring drives both ICI links while the VPU folds; a regression
        here means the overlap broke even if parity still holds)."""
        import time

        from oap_mllib_tpu.config import set_config
        from oap_mllib_tpu.ops import kmeans_ops
        from oap_mllib_tpu.parallel.mesh import get_mesh

        mesh, world = ring_mesh
        n, d, k = 1 << 17, 256, 256
        data = rng.normal(size=(n, d)).astype(np.float32)
        w = np.ones((n,), np.float32)
        c0 = data[:k]
        m = get_mesh()
        xs = jax.device_put(
            jnp.asarray(data), NamedSharding(m, P("data", "model"))
        )
        ws = jax.device_put(jnp.asarray(w), NamedSharding(m, P("data")))
        tol = jnp.asarray(0.0, jnp.float32)

        def wall(iters=24):
            r = kmeans_ops.lloyd_run_model_sharded(
                xs, ws, jnp.asarray(c0), iters, tol, m, "data", "model"
            )
            np.asarray(r[0])  # block
            t0 = time.perf_counter()
            r = kmeans_ops.lloyd_run_model_sharded(
                xs, ws, jnp.asarray(c0), iters, tol, m, "data", "model"
            )
            np.asarray(r[0])
            return time.perf_counter() - t0

        t_ring = wall()
        set_config(ring_reduction="off")
        t_psum = wall()
        set_config(ring_reduction="auto")
        # generous bound: the fused ring must at least break even (the
        # profile_kernels overlap sweep quantifies the actual win)
        assert t_ring <= t_psum * 1.25, (t_ring, t_psum)
