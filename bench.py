#!/usr/bin/env python
"""Headline benchmark: K-Means iterations/second on TPU.

Config follows the BASELINE.md north star (K-Means iters/sec, large dense
matrix, k=1000) scaled to one chip's HBM: 1M x 256 float32, k=1000,
row-chunked Lloyd so the (n, k) distance matrix never materializes.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "iters/sec", "vs_baseline": N}

``vs_baseline`` is the speedup over the CPU reference path (the vanilla
NumPy Lloyd this framework falls back to — the analog of the reference
project's vanilla Spark MLlib baseline, whose repo publishes no numbers,
BASELINE.md), measured live on a subsample and scaled linearly to the full
row count.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from oap_mllib_tpu.ops import kmeans_ops

    n, d, k = 1 << 20, 256, 1000
    row_chunks = 16
    iters = 10
    rng = np.random.default_rng(0)
    # blob-ish data so assignments are non-degenerate
    proto = rng.normal(size=(k, d)).astype(np.float32)
    x = proto[rng.integers(k, size=n)] + rng.normal(size=(n, d)).astype(np.float32) * 0.3
    w = np.ones((n,), np.float32)
    init = proto + rng.normal(size=(k, d)).astype(np.float32) * 0.01

    xj = jax.device_put(jnp.asarray(x))
    wj = jnp.asarray(w)
    cj = jnp.asarray(init)
    tol = jnp.asarray(0.0, jnp.float32)  # tol=0: never converge early

    from oap_mllib_tpu.config import get_config

    precision = get_config().matmul_precision  # env-overridable via config

    def run(max_iter):
        c, it, cost, _ = kmeans_ops.lloyd_run(
            xj, wj, cj, max_iter, tol, row_chunks, precision
        )
        # fetch scalars: on remote-execution backends block_until_ready can
        # be a no-op, so only a host transfer truly synchronizes
        return np.asarray(c), int(it), float(cost)

    # Warm up the SAME static-arg variant that gets timed: max_iter is a
    # static jit arg, so run(1) and run(iters) are different compilations.
    run(iters)
    t0 = time.perf_counter()
    _, it, cost = run(iters)
    dt = time.perf_counter() - t0
    iters_per_sec = it / dt

    # CPU reference baseline: one Lloyd pass on a subsample, scaled to n.
    sub = 1 << 14
    xs, ws = x[:sub], w[:sub]
    from oap_mllib_tpu.fallback.kmeans_np import lloyd_np

    t0 = time.perf_counter()
    lloyd_np(xs.astype(np.float64), init.astype(np.float64), 1, 0.0, ws)
    t_cpu_sub = time.perf_counter() - t0
    cpu_iters_per_sec = 1.0 / (t_cpu_sub * (n / sub))

    print(
        json.dumps(
            {
                "metric": "kmeans_1Mx256_k1000_iters_per_sec",
                "value": round(iters_per_sec, 4),
                "unit": "iters/sec",
                "vs_baseline": round(iters_per_sec / cpu_iters_per_sec, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
