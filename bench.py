#!/usr/bin/env python
"""Benchmarks: K-Means / PCA / ALS on the accelerated path.

Default (driver mode) prints ONE JSON line — the headline metric from
BASELINE.md's north star (K-Means iters/sec, 1M x 256 f32, k=1000,
row-chunked Lloyd so the (n, k) distance matrix never materializes):

  {"metric": ..., "value": N, "unit": "iters/sec", "vs_baseline": N, ...}

``python bench.py --all`` regenerates every number in BASELINE.md's main
measured table — one JSON line per metric (K-Means both precision tiers,
PCA 1M x 128 plus the largest-d single-chip proxy with per-phase slope
attribution, ALS at MovieLens-1M and -25M scale) — the analog of the
reference's per-phase timing printouts (PCADALImpl.cpp:71-159,
ALSDALImpl.cpp:429-436), but recorded instead of scrolled away.
(BASELINE's feature sections — streamed ALS, item layouts, the
randomized PCA solver — record their own scripted measurements inline;
``--mesh N`` runs the weak-scaling harness.)

K-Means/PCA lines report achieved TFLOP/s and MFU against the chip's bf16
peak.  Timings are best-of-3: the device tunnel used in this environment
adds run-to-run jitter of up to ~30%, and the max over repeats is the
honest kernel speed.  ``vs_baseline`` is the speedup over this framework's
own CPU/NumPy reference path (the vanilla-Spark-MLlib analog; the
reference repo publishes no numbers, BASELINE.md), measured live on a
subsample and scaled linearly to the full size.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

# bf16 peak FLOP/s by device kind (the MFU denominator)
_PEAK = {
    "TPU v6": 918e12,
    "TPU v5p": 459e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
}


def _peak_flops():
    import jax

    kind = jax.devices()[0].device_kind
    for key, val in _PEAK.items():
        if kind.startswith(key):
            return val
    return 197e12  # conservative default


def _best_of(fn, reps=3, warm=True):
    """Best wall time over reps (see module docstring on tunnel jitter)."""
    if warm:
        fn()  # warm-up/compile of the exact timed variant
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


# measured single-chip ALS gather ceiling (BASELINE round-5: XLA's TPU
# gather moves padded edge indices at ~250M indices/s regardless of
# layout; the bound is per-index, not per-byte)
_ALS_GATHER_CEILING = 250e6


def _bound_extras(kind, achieved, bound):
    """Uniform achieved-vs-bound annotation (VERDICT r5 item 5): every
    per-algorithm headline line names its achieved rate, the bound it is
    measured against, and the fraction — so a round-over-round regression
    in ANY algorithm surfaces in the driver-captured JSON, not just in
    BASELINE prose."""
    return {
        "bound_kind": kind,
        "achieved": round(achieved, 3),
        "bound": round(bound, 3),
        "bound_frac": round(achieved / bound, 4) if bound else None,
    }


def _sanitizers_state() -> str:
    """The armed sanitizer set as a stable string ("off" when empty) —
    recorded in every bench JSON line so runs are comparable: the
    collective sanitizer adds a cross-check gather per host collective
    and the retrace guard changes compile behavior, so numbers from
    runs with different sanitizer sets must never be diffed silently."""
    from oap_mllib_tpu.utils import sanitizers

    names = sorted(sanitizers.enabled_set())
    return ",".join(names) if names else "off"


def _emit(metric, value, unit, vs_baseline, **extra):
    import jax

    line = {
        "metric": metric,
        "value": round(value, 4),
        "unit": unit,
        "vs_baseline": round(vs_baseline, 2),
        "sanitizers": _sanitizers_state(),
        # every line names its backend so trajectory tooling
        # (dev/bench_regress.py) never diffs numbers across backends
        "backend": jax.default_backend(),
    }
    if "locks" in _sanitizers_state():
        # the locks sanitizer's hold-time tail rides the line so a
        # locks-armed capture explains its own latency inflation
        from oap_mllib_tpu.utils import locktrace

        line["lock_hold_p99_ms"] = round(
            locktrace.hold_quantile(0.99) * 1e3, 4)
    if "kernel" in extra:
        # every kernel-bearing line names the autotune policy it ran
        # under — numbers from a swept/pinned run must never be diffed
        # silently against hand-picked-default numbers
        from oap_mllib_tpu.config import get_config

        line["tuning"] = get_config().tuning.split(":", 1)[0]
    line.update(extra)
    print(json.dumps(line), flush=True)


def _compile_extras(timings, phase, cache_delta=None):
    """Compile-amortization report for a fit (rides next to the overlap
    metrics): the ``<phase>/compile`` vs ``/execute`` wall split the
    program-cache launch wrappers record (utils/progcache.launch —
    compile = first-seen-program launches, execute = cache-hit
    launches), plus the fit's registry hit rate."""
    out = {}
    split = timings.compile_split(phase) if timings is not None else None
    if split is not None:
        out["compile_sec"] = round(split["compile"], 3)
        out["execute_sec"] = round(split["execute"], 3)
    if cache_delta:
        out["progcache_hits"] = cache_delta["hits"]
        out["progcache_misses"] = cache_delta["misses"]
        if cache_delta.get("hit_rate") is not None:
            out["progcache_hit_rate"] = round(cache_delta["hit_rate"], 3)
    return out


# ---------------------------------------------------------------------------
# K-Means (headline)
# ---------------------------------------------------------------------------


def bench_kmeans(precision="highest", cpu_ips=None, extra=None,
                 policy="f32"):
    import jax
    import jax.numpy as jnp

    from oap_mllib_tpu.ops import kmeans_ops

    n, d, k = 1 << 20, 256, 1000
    # 100 iterations per timed run: the remote-device tunnel adds
    # ~300-400 ms of dispatch+fetch latency per call, so a short window
    # understates steady-state throughput several-fold (real fits at this
    # scale run the loop for hundreds of iterations).  The executed
    # n_iter is divided by, so early exact convergence cannot inflate the
    # number (the round-1/2 bug).
    iters = 100
    # Accelerator-less hosts (CI containers, laptops): the full headline
    # shape is ~2 TFLOP/iteration — hours of CPU for one recorded line.
    # Record a CPU-affordable proxy instead, under its OWN metric name
    # (``*_cpuproxy``), so the perf trajectory still gets a point per
    # round everywhere while dev/bench_regress.py never diffs CPU proxy
    # numbers against accelerator rounds (metrics compare by exact name).
    cpu_proxy = jax.default_backend() == "cpu"
    if cpu_proxy:
        n, k, iters = 1 << 17, 256, 10
    rng = np.random.default_rng(0)
    # blob-ish data so assignments are non-degenerate
    proto = rng.normal(size=(k, d)).astype(np.float32)
    x = proto[rng.integers(k, size=n)] + rng.normal(size=(n, d)).astype(np.float32) * 0.3
    w = np.ones((n,), np.float32)
    # RANDOM-ROW init, not proto+epsilon: a near-optimal init converges in
    # ~2 Lloyd iterations and tol=0 does NOT prevent the stop (exactly-zero
    # moves satisfy <= 0), so rounds 1-2 timed 2 iterations while dividing
    # by 10 — every prior recorded kmeans bench number was inflated.  The
    # actual executed n_iter is now fetched, divided by, and recorded.
    init = x[rng.choice(n, size=k, replace=False)]

    xj = jax.device_put(jnp.asarray(x))
    wj = jnp.asarray(w)
    cj = jnp.asarray(init)
    tol = jnp.asarray(0.0, jnp.float32)
    chunks = kmeans_ops.auto_row_chunks(n, k)

    # the estimator's own dispatch rule — one shared helper, cannot diverge
    use_pallas = kmeans_ops.use_pallas_path("auto", d, k, precision, np.float32)

    def run():
        if use_pallas:
            from oap_mllib_tpu.ops.pallas.kmeans_kernel import lloyd_run_pallas

            c, it, cost, _ = lloyd_run_pallas(xj, wj, cj, iters, tol, mode=precision)
        else:
            c, it, cost, _ = kmeans_ops.lloyd_run(
                xj, wj, cj, iters, tol, chunks, precision, policy=policy
            )
        # fetch centers: on remote-execution backends block_until_ready can
        # be a no-op, so only a host transfer truly synchronizes
        return np.asarray(c), int(it)

    from oap_mllib_tpu.utils import progcache

    xla_before = progcache.xla_compile_count()
    t0 = time.perf_counter()
    n_iter = run()[1]  # warm-up/compile; n_iter is deterministic
    t_first = time.perf_counter() - t0  # first call = trace+compile+run
    # 5 reps: the tunnel's per-call latency varies ~10% run-to-run and
    # this is THE recorded headline — extra reps are cheap insurance
    reps = 5
    dt = _best_of(lambda: run()[0], reps=reps, warm=False)
    iters_per_sec = n_iter / dt
    # compile-amortized throughput: every iteration this process ran,
    # divided by every second it spent (first-call compile included) —
    # what a one-shot caller actually gets vs the steady-state headline
    amortized_ips = n_iter * (reps + 1) / (t_first + reps * dt)
    flops = 2 * 2 * n * k * d  # two n*k*d matmuls per iteration
    tflops = flops * iters_per_sec / 1e12

    if cpu_ips is None:
        # CPU reference baseline: one Lloyd pass on a subsample, scaled to n
        sub = 1 << 14
        from oap_mllib_tpu.fallback.kmeans_np import lloyd_np

        t0 = time.perf_counter()
        lloyd_np(x[:sub].astype(np.float64), init.astype(np.float64), 1, 0.0, w[:sub])
        t_cpu_sub = time.perf_counter() - t0
        cpu_ips = 1.0 / (t_cpu_sub * (n / sub))

    suffix = "" if precision == "high" else f"_{precision}"
    size = f"{n >> 20}M" if n >= (1 << 20) else f"{n >> 10}K"
    metric = f"kmeans_{size}x{d}_k{k}_iters_per_sec"
    if cpu_proxy:
        metric += "_cpuproxy"
    # the recorded precision follows the COMPUTE POLICY (no longer
    # hardwired to a tier): an f32 policy keeps the legacy tier string
    # for BASELINE.md row continuity, a reduced policy names itself
    _emit(
        f"{metric}{suffix}",
        iters_per_sec,
        "iters/sec",
        iters_per_sec / cpu_ips,
        tflops=round(tflops, 1),
        mfu=round(tflops * 1e12 / _peak_flops(), 3),
        **_bound_extras("bf16_peak_tflops", tflops, _peak_flops() / 1e12),
        precision=precision if policy == "f32" else policy,
        compute_precision=policy,
        matmul_tier=precision,
        n_iter=n_iter,
        kernel="pallas" if use_pallas else "xla",
        compile_sec=round(max(t_first - dt, 0.0), 2),
        amortized_iters_per_sec=round(amortized_ips, 3),
        xla_compiles=progcache.xla_compile_count() - xla_before,
        **(extra or {}),
    )
    return iters_per_sec, cpu_ips


# ---------------------------------------------------------------------------
# PCA
# ---------------------------------------------------------------------------


def _slope(run_with_reps, r1=1, target_delta=0.8, r2_cap=2048, reps=3):
    """Per-op seconds via an in-jit repeat slope: (t(r2) - t(r1)) /
    (r2 - r1) cancels the constant per-call tunnel dispatch+fetch
    (~0.1-0.4 s) that a single-call wall would book against the kernel —
    the same protocol as the K-Means kernel table.

    Two hard-won constraints: the repeat count must be a RUNTIME loop
    bound (lax.fori_loop), not a static scan length — eigh at d=2048
    takes ~4 minutes to compile on this backend, so both window sizes
    must share one executable — and the window must be WORK-CALIBRATED
    (a quick probe sizes r2 so the delta is ~``target_delta`` seconds):
    fixed small windows put ms-scale per-op deltas under the tunnel's
    10-30 ms jitter and read as zero."""
    run_with_reps(r1)  # one compile (dynamic trip count) + warm
    t_r1 = _best_of(lambda: run_with_reps(r1), reps=2, warm=False)
    probe_r = min(r2_cap, 4 * r1 + 8)
    t_probe = _best_of(lambda: run_with_reps(probe_r), reps=2, warm=False)
    per = max((t_probe - t_r1) / (probe_r - r1), 1e-5)
    r2 = min(r2_cap, r1 + max(8, int(target_delta / per)))
    # the probe's r1 samples count toward the final best-of (no reason to
    # pay the ~0.1-0.4 s dispatch for duplicate r1 windows)
    t1 = min(t_r1, _best_of(lambda: run_with_reps(r1), reps=1, warm=False))
    t2 = _best_of(lambda: run_with_reps(r2), reps=reps, warm=False)
    return max(t2 - t1, 1e-9) / (r2 - r1)


def bench_pca(n=1 << 20, d=128):
    """PCA with per-phase kernel attribution (VERDICT r3 item 2): the
    covariance Gram and the eigh are slope-measured SEPARATELY inside
    jitted repeat loops, so the recorded numbers are kernel times — the
    round-3 single-wall figure at 1M x 128 was mostly the ~0.1-0.4 s
    device-tunnel dispatch (the 33-GFLOP Gram is sub-ms of MXU time).
    The end-to-end wall (one call incl. dispatch + fetch, what a remote
    caller sees per fit) is still the headline value for continuity."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    from oap_mllib_tpu.config import get_config
    from oap_mllib_tpu.ops import pca_ops

    rng = np.random.default_rng(1)
    x = rng.normal(size=(n, d)).astype(np.float32)
    xj = jax.device_put(jnp.asarray(x))
    mask = jnp.ones((n,), jnp.float32)
    n_rows = jnp.asarray(float(n), jnp.float32)

    def run():
        cov, _ = pca_ops.covariance(xj, mask, n_rows)
        vals, _ = pca_ops.eigh_descending(cov)
        return np.asarray(vals)  # host fetch = sync

    dt = _best_of(run)

    # phase 1: covariance (two-pass centered Gram at HIGHEST).  The
    # carry-perturbed mask (numerically nil) defeats loop-invariant code
    # motion hoisting the otherwise-identical Gram out of the loop.
    @functools.partial(jax.jit)
    def cov_reps(xr, m, nr, reps):
        def body(i, acc):
            cov, _ = pca_ops.covariance(xr, m + acc[0, 0] * 1e-30, nr)
            return acc + cov

        return lax.fori_loop(
            0, reps, body, jnp.zeros((d, d), xr.dtype)
        )

    cov_sec = _slope(lambda r: np.asarray(cov_reps(xj, mask, n_rows, r)))

    # phase 2: eigh (the finalizeCompute analog), same protocol
    cov0 = jax.device_put(pca_ops.covariance(xj, mask, n_rows)[0])

    @functools.partial(jax.jit)
    def eigh_reps(cov, reps):
        def body(i, acc):
            _, vecs = pca_ops.eigh_descending(cov + acc * 1e-30)
            return acc + vecs

        return lax.fori_loop(0, reps, body, jnp.zeros_like(cov))

    eigh_sec = _slope(lambda r: np.asarray(eigh_reps(cov0, r)))

    cov_flops = 2 * n * d * d  # centered Gram matmul (mean pass is O(nd))
    cov_tflops = cov_flops / cov_sec / 1e12

    # NumPy f64 baseline: covariance on a subsample scaled linearly in n
    # (Gram is linear in n); eigh timed once at full size (it is O(d^3),
    # independent of n — scaling it would overstate the baseline)
    sub = min(n, 1 << 16)
    t0 = time.perf_counter()
    xs = x[:sub].astype(np.float64)
    mu = xs.mean(axis=0)
    cov_np = (xs.T @ xs - sub * np.outer(mu, mu)) / (sub - 1)
    t_cov = (time.perf_counter() - t0) * (n / sub)
    t0 = time.perf_counter()
    np.linalg.eigh(cov_np)
    t_cpu = t_cov + (time.perf_counter() - t0)

    size = f"{n >> 20}M" if n >= (1 << 20) else f"{n >> 10}k"
    _emit(
        f"pca_{size}x{d}_cov_eigh_sec",
        dt,
        "sec",
        t_cpu / dt,
        cov_sec=round(cov_sec, 5),
        eigh_sec=round(eigh_sec, 5),
        dispatch_sec=round(max(dt - cov_sec - eigh_sec, 0.0), 4),
        cov_tflops=round(cov_tflops, 1),
        cov_mfu=round(cov_tflops * 1e12 / _peak_flops(), 3),
        # which Gram kernel the dispatch rule picked for this shape —
        # the ISSUE 9 fused Pallas moments kernel on TPU, XLA elsewhere
        kernel=(
            "pallas"
            if pca_ops.use_pallas_gram(
                get_config().pca_kernel, d, "highest", np.float32
            )
            else "xla"
        ),
        # eigh's share of the end-to-end wall: a growing share at fixed
        # d means the O(d^3) finalize (not the Gram) regressed
        eigh_wall_share=round(eigh_sec / dt, 4),
        **_bound_extras("bf16_peak_tflops", cov_tflops,
                        _peak_flops() / 1e12),
    )
    return dt


# ---------------------------------------------------------------------------
# ALS
# ---------------------------------------------------------------------------


def _als_solve_extras(n_users, n_items, rank, sec_per_iter):
    """MFU-style annotation for the ALS normal-equation SOLVE kernel
    (ISSUE 9): analytic solve+assembly FLOPs per iteration — both
    halves Cholesky-factor (2/3·r³) and doubly-substitute (4·r²) one
    system per user/item row — over the iteration wall, next to the
    gather bound.  A lower bound on solve intensity (the wall includes
    the moment build), but a regression in the fused Pallas solve
    surfaces as a falling solve_mfu at fixed shape."""
    from oap_mllib_tpu.ops.als_ops import resolve_solve_kernel

    flops = (n_users + n_items) * (
        (2.0 / 3.0) * rank ** 3 + 4.0 * rank ** 2
    )
    solve_tflops = flops / sec_per_iter / 1e12
    return {
        "solve_tflops": round(solve_tflops, 4),
        "solve_mfu": round(solve_tflops * 1e12 / _peak_flops(), 6),
        "solve_kernel": resolve_solve_kernel(rank, np.float32),
    }


def bench_als():
    """MovieLens-1M scale: 6040 users x 3706 items, 1M ratings, rank 10,
    implicit, alpha=40 (the reference examples' DAL-path config,
    examples/als-pyspark/als-pyspark.py:52-54)."""
    import jax
    import jax.numpy as jnp

    from oap_mllib_tpu.fallback import als_np
    from oap_mllib_tpu.ops import als_ops

    n_users, n_items, nnz, rank = 6040, 3706, 1_000_000, 10
    # 25-iteration window: ALS runs its whole loop in ONE jitted call (no
    # early exit — lax.scan over max_iter), so like the K-Means bench the
    # window must be long enough that the device tunnel's per-call
    # dispatch latency (~75 ms) doesn't dominate the per-iteration figure
    iters = 25
    rng = np.random.default_rng(2)
    users = rng.integers(n_users, size=nnz).astype(np.int32)
    items = rng.integers(n_items, size=nnz).astype(np.int32)
    ratings = (rng.random(nnz) * 4 + 1).astype(np.float32)
    x0 = als_np.init_factors(n_users, rank, 0)
    y0 = als_np.init_factors(n_items, rank, 1)

    # grouped-edge layout — the estimator's actual single-device hot path
    by_user = als_ops.build_grouped_edges(users, items, ratings, n_users)
    by_item = als_ops.build_grouped_edges(items, users, ratings, n_items)
    dev = tuple(jax.device_put(jnp.asarray(a)) for a in (*by_user, *by_item))
    x0j, y0j = jnp.asarray(x0), jnp.asarray(y0)

    def run():
        x, y = als_ops.als_run_grouped(
            *dev, x0j, y0j, n_users, n_items, iters, 0.1, 40.0, True
        )
        return np.asarray(x)

    dt = _best_of(run)
    sec_per_iter = dt / iters

    # NumPy fallback: one full-size iteration (no subsample scaling — the
    # per-user/item solve cost is independent of nnz, so scaling a
    # subsample time would overstate the baseline)
    t0 = time.perf_counter()
    als_np.als_np(
        users, items, ratings, n_users, n_items, rank,
        max_iter=1, reg=0.1, alpha=40.0, implicit=True, seed=0, init=(x0, y0),
    )
    t_cpu_iter = time.perf_counter() - t0

    # per iteration both halves gather their PADDED edge lists' source
    # factors once — the measured single-chip bottleneck (BASELINE:
    # "the grouped iteration is gather-bound")
    gathered = by_user[0].size + by_item[0].size
    _emit(
        "als_ml1m_implicit_sec_per_iter",
        sec_per_iter,
        "sec/iter",
        t_cpu_iter / sec_per_iter,
        **_bound_extras("gather_indices_per_sec",
                        gathered / sec_per_iter, _ALS_GATHER_CEILING),
        **_als_solve_extras(n_users, n_items, rank, sec_per_iter),
    )
    return sec_per_iter


def bench_als_large():
    """MovieLens-25M scale: 162,541 users x 59,047 items, 25M ratings,
    rank 10, implicit — the single-chip scale proof (the G-blocked
    grouped partials keep live intermediates ~256 MB; unchunked, lane
    padding alone needed 21 GB and OOM'd).  Item popularity is zipf(1.3)
    so the padding guard sees a real long tail."""
    import jax
    import jax.numpy as jnp

    from oap_mllib_tpu.fallback import als_np
    from oap_mllib_tpu.ops import als_ops

    n_users, n_items, nnz, rank = 162_541, 59_047, 25_000_000, 10
    iters = 10  # ~2.7 s per call: dispatch latency is already <5% here
    rng = np.random.default_rng(3)
    users = rng.integers(n_users, size=nnz).astype(np.int32)
    items = (np.random.default_rng(4).zipf(1.3, size=nnz) % n_items).astype(
        np.int32
    )
    ratings = (rng.random(nnz) * 4 + 1).astype(np.float32)
    x0 = als_np.init_factors(n_users, rank, 0)
    y0 = als_np.init_factors(n_items, rank, 1)

    by_user = als_ops.build_grouped_edges(users, items, ratings, n_users)
    by_item = als_ops.build_grouped_edges(items, users, ratings, n_items)
    dev = tuple(jax.device_put(jnp.asarray(a)) for a in (*by_user, *by_item))
    x0j, y0j = jnp.asarray(x0), jnp.asarray(y0)

    def run():
        x, y = als_ops.als_run_grouped(
            *dev, x0j, y0j, n_users, n_items, iters, 0.1, 40.0, True
        )
        return np.asarray(x)

    dt = _best_of(run)
    sec_per_iter = dt / iters

    # CPU reference: one iteration on a 1/25 subsample with the full
    # user/item universe — per-row solve cost dominates (162k + 59k
    # solves happen regardless of nnz), so this UNDERSTATES the full-size
    # CPU time; the recorded speedup is therefore a floor
    sub = nnz // 25
    t0 = time.perf_counter()
    als_np.als_np(
        users[:sub], items[:sub], ratings[:sub], n_users, n_items, rank,
        max_iter=1, reg=0.1, alpha=40.0, implicit=True, seed=0, init=(x0, y0),
    )
    t_cpu_iter = time.perf_counter() - t0

    gathered = by_user[0].size + by_item[0].size
    _emit(
        "als_ml25m_implicit_sec_per_iter",
        sec_per_iter,
        "sec/iter",
        t_cpu_iter / sec_per_iter,
        **_bound_extras("gather_indices_per_sec",
                        gathered / sec_per_iter, _ALS_GATHER_CEILING),
        **_als_solve_extras(n_users, n_items, rank, sec_per_iter),
    )
    return sec_per_iter


# ---------------------------------------------------------------------------
# Multi-chip weak-scaling harness (bench.py --mesh N)
# ---------------------------------------------------------------------------


def _mesh_of(m):
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:m]).reshape(m), ("data",))


def bench_mesh(n_devices: int, backend: str = "cpu", sizes: str = "small"):
    """Weak-scaling protocol over 1..n_devices ranks: per-rank work is
    FIXED and the global problem grows with the mesh, for all three
    estimator kernels.  One JSON line per (kernel, mesh) with wall time,
    per-rank work, and the analytic per-iteration collective payload
    (allreduce counted 2x payload x (m-1)/m).

    The same entry point runs unchanged on a real slice
    (``--mesh-backend real``); with ``backend="cpu"`` (the default, and
    what CI pins at N=8) the ranks are VIRTUAL CPU devices sharing one
    host — wall times then measure protocol/compute overheads, NOT ICI
    scaling, and every line carries ``"virtual_cpu": true`` to say so.
    ``sizes="big"`` selects slice-scale shapes for real hardware."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"--mesh {n_devices} needs {n_devices} devices, backend has "
            f"{len(jax.devices())} (forcing the virtual CPU mesh failed — "
            "a backend initialized before bench_mesh could configure it?)"
        )
    virtual = jax.default_backend() == "cpu" and backend == "cpu"
    big = sizes == "big"
    rng = np.random.default_rng(7)

    meshes = [1]
    while meshes[-1] * 2 <= n_devices:
        meshes.append(meshes[-1] * 2)
    if meshes[-1] != n_devices:  # --mesh 6: [1, 2, 4, 6], never skip N
        meshes.append(n_devices)

    # -- K-Means: per-rank rows fixed -------------------------------------
    from oap_mllib_tpu.ops import kmeans_ops

    rows_per_rank, d, k = (1 << 18, 256, 256) if big else (1 << 14, 32, 16)
    iters = 10
    for m in meshes:
        n = rows_per_rank * m
        x = rng.normal(size=(n, d)).astype(np.float32)
        init = x[rng.choice(n, size=k, replace=False)]
        mesh = _mesh_of(m)
        xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("data", None)))
        ws = jax.device_put(
            jnp.ones((n,), jnp.float32), NamedSharding(mesh, P("data"))
        )
        cj = jnp.asarray(init)
        tol = jnp.asarray(0.0, jnp.float32)
        chunks = kmeans_ops.auto_row_chunks(rows_per_rank, k)

        def run():
            c, it, _, _ = kmeans_ops.lloyd_run(
                xs, ws, cj, iters, tol, chunks, "highest"
            )
            return np.asarray(c), int(it)

        n_iter = run()[1]
        dt = _best_of(lambda: run()[0], reps=2, warm=False)
        _emit(
            "mesh_scaling_kmeans", dt / max(n_iter, 1), "sec/iter", 1.0,
            mesh=m, per_rank_rows=rows_per_rank, d=d, k=k,
            collective_bytes_per_iter=int(
                2 * (k * d + k) * 4 * (m - 1) / max(m, 1)
            ),
            virtual_cpu=virtual,
        )

    # -- PCA: per-rank rows fixed -----------------------------------------
    from oap_mllib_tpu.ops import pca_ops

    rows_per_rank, d = (1 << 18, 512) if big else (1 << 15, 128)
    for m in meshes:
        n = rows_per_rank * m
        x = rng.normal(size=(n, d)).astype(np.float32)
        mesh = _mesh_of(m)
        xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("data", None)))
        ws = jax.device_put(
            jnp.ones((n,), jnp.float32), NamedSharding(mesh, P("data"))
        )
        nr = jnp.asarray(float(n), jnp.float32)

        def run():
            cov, _ = pca_ops.covariance(xs, ws, nr)
            return np.asarray(cov)

        dt = _best_of(run, reps=2)
        _emit(
            "mesh_scaling_pca_cov", dt, "sec", 1.0,
            mesh=m, per_rank_rows=rows_per_rank, d=d,
            collective_bytes_per_iter=int(
                2 * (d * d + d) * 4 * (m - 1) / max(m, 1)
            ),
            virtual_cpu=virtual,
        )

    # -- ALS: per-rank edges + user rows fixed, replicated item layout ----
    from oap_mllib_tpu.ops import als_block

    edges_per_rank, users_per_rank, n_items, r = (
        (1 << 21, 1 << 18, 1 << 16, 10) if big else (100_000, 10_000, 5_000, 8)
    )
    als_iters = 3
    for m in meshes:
        nnz = edges_per_rank * m
        n_users = users_per_rank * m
        u = rng.integers(0, n_users, nnz).astype(np.int64)
        i = rng.integers(0, n_items, nnz).astype(np.int64)
        rr = (rng.random(nnz) * 4 + 1).astype(np.float32)
        mesh = _mesh_of(m)
        u_loc, i_glob, conf, valid, offsets, upb = (
            als_block.prepare_block_inputs(u, i, rr, mesh, n_users)
        )
        grouped = als_block.prepare_grouped_inputs(
            u_loc, i_glob, conf, valid, mesh, upb, n_items
        )
        from jax.sharding import NamedSharding as NS

        x0 = jax.device_put(
            (rng.normal(size=(mesh.shape["data"] * upb, r)) * 0.1).astype(
                np.float32
            ),
            NS(mesh, P("data", None)),
        )
        y0 = jax.device_put(
            (rng.normal(size=(n_items, r)) * 0.1).astype(np.float32),
            NS(mesh, P()),
        )

        def run():
            bx, by = als_block.als_block_run_grouped(
                grouped, x0, y0, als_iters, 0.1, 1.0, mesh, implicit=True
            )
            return np.asarray(by)

        dt = _best_of(run, reps=2)
        _emit(
            "mesh_scaling_als", dt / als_iters, "sec/iter", 1.0,
            mesh=m, per_rank_edges=edges_per_rank,
            per_rank_users=users_per_rank, n_items=n_items, rank=r,
            item_layout="replicated",
            collective_bytes_per_iter=int(
                2 * (n_items * r * (r + 1) + r * r) * 4 * (m - 1) / max(m, 1)
            ),
            virtual_cpu=virtual,
        )

        # the 2-D item-sharded layout on the same edges/sizes: second
        # shuffle by item block, Y block-sharded, all_gather exchanges
        i_loc, u_glob, conf_i, valid_i, _, ipb = (
            als_block.prepare_block_inputs(i, u, rr, mesh, n_items)
        )
        grouped2 = als_block.prepare_grouped_inputs_2d(
            u_loc, i_glob, conf, valid, i_loc, u_glob, conf_i, valid_i,
            mesh, upb, ipb,
        )
        y0_sh = jax.device_put(
            (rng.normal(size=(m * ipb, r)) * 0.1).astype(np.float32),
            NS(mesh, P("data", None)),
        )

        def run_sh():
            bx, by = als_block.als_block_run_grouped_2d(
                grouped2, x0, y0_sh, als_iters, 0.1, 1.0, mesh,
                implicit=True,
            )
            return np.asarray(by)

        dt = _best_of(run_sh, reps=2)
        _emit(
            "mesh_scaling_als", dt / als_iters, "sec/iter", 1.0,
            mesh=m, per_rank_edges=edges_per_rank,
            per_rank_users=users_per_rank, n_items=n_items, rank=r,
            item_layout="sharded",
            # two tiled all_gathers (X, Y) + TWO r*r Gram allreduces
            # (allreduce = 2x payload, the same convention as every
            # other formula in this file)
            collective_bytes_per_iter=int(
                ((n_users + n_items) * r + 4 * r * r)
                * 4 * (m - 1) / max(m, 1)
            ),
            virtual_cpu=virtual,
        )

        # the streamed out-of-core composition on the same edges: each
        # rank's grouped layouts stay HOST-resident and stream through
        # its device in chunks (ops/als_block_stream); the collective
        # structure matches the replicated run above, so the delta vs
        # mesh_scaling_als is the upload-per-iteration price
        from oap_mllib_tpu.ops import als_block_stream

        lay = als_block_stream.prepare_streamed_block_layouts(
            u, i, rr, n_users, n_items, mesh, r, item_sharded=False
        )

        def run_st():
            bx, by = als_block_stream.als_block_run_streamed(
                lay, x0, y0, als_iters, 0.1, 1.0, mesh, implicit=True
            )
            return np.asarray(by)

        dt = _best_of(run_st, reps=2)
        # one instrumented run for the prefetch split: how much of the
        # per-iteration upload price the pipeline hides behind the
        # moment kernels (the delta vs mesh_scaling_als is the price;
        # overlap_efficiency is the hidden fraction)
        from oap_mllib_tpu.utils.timing import Timings

        t_st = Timings()
        als_block_stream.als_block_run_streamed(
            lay, x0, y0, als_iters, 0.1, 1.0, mesh, implicit=True,
            timings=t_st,
        )
        eff = t_st.overlap_efficiency("als_iterations")
        sub = t_st.subphases("als_iterations")
        _emit(
            "mesh_scaling_als_streamed", dt / als_iters, "sec/iter", 1.0,
            mesh=m, per_rank_edges=edges_per_rank,
            per_rank_users=users_per_rank, n_items=n_items, rank=r,
            item_layout="replicated", virtual_cpu=virtual,
            overlap_efficiency=None if eff is None else round(eff, 3),
            transfer_sec=round(sub.get("transfer", 0.0), 3),
        )


# ---------------------------------------------------------------------------
# North-star streamed scale (bench.py --streamed ROWS)
# ---------------------------------------------------------------------------


def bench_streamed(rows: int, d: int = 256, k: int = 1000,
                   max_iter: int = 2):
    """Streamed K-Means + PCA at north-star row counts (BASELINE.json's
    100M x 256 config): a generator-backed ChunkSource synthesizes the
    table on the fly — host RAM holds one ~1 GB base buffer and one
    chunk, device HBM one chunk + the running state — so THE SAME
    command scales to any row count the wall clock affords:

        python bench.py --streamed 100000000     # full north star (pod host)
        python bench.py --streamed 10000000      # tunnel-affordable point

    Emits the measured host->device bandwidth first (on the axon tunnel
    used here that bandwidth, not compute, bounds the per-pass time —
    the JSON records both so a reader can project a directly-attached
    host; compute per pass at k=1000 is ~0.2 s, BASELINE).
    """
    import jax

    from oap_mllib_tpu.data.stream import ChunkSource
    from oap_mllib_tpu.models.kmeans import KMeans
    from oap_mllib_tpu.models.pca import PCA

    if rows < k:
        raise SystemExit(
            f"--streamed ROWS must be >= k={k} (got {rows}); the point of "
            "this mode is north-star row counts"
        )
    chunk_rows = 1 << 16
    base_n = min(rows, 1 << 20)
    rng = np.random.default_rng(0)
    proto = rng.normal(size=(k, d)).astype(np.float32) * 4
    x_base = (
        proto[rng.integers(k, size=base_n)]
        + rng.normal(size=(base_n, d)).astype(np.float32) * 0.3
    )

    def gen():
        remaining = rows
        while remaining > 0:
            take = min(base_n, remaining)
            yield x_base[:take]
            remaining -= take

    # raw ingest bandwidth at the fit's own chunk size — the bound this
    # environment puts on every per-pass number below
    probe = x_base[:chunk_rows]
    _ = np.asarray(jax.device_put(probe)[0, 0])  # warm (sync via fetch)
    t_up = _best_of(
        lambda: np.asarray(jax.device_put(probe)[0, 0]), reps=3, warm=False
    )
    mbps = probe.nbytes / t_up / 1e6
    _emit("host_to_device_MBps", mbps, "MB/s", 1.0,
          chunk_mb=probe.nbytes >> 20)

    # CPU per-pass reference (one Lloyd pass on a subsample, scaled)
    sub = min(1 << 14, base_n)
    from oap_mllib_tpu.fallback.kmeans_np import lloyd_np

    t0 = time.perf_counter()
    lloyd_np(
        x_base[:sub].astype(np.float64),
        x_base[rng.choice(base_n, size=k, replace=False)].astype(np.float64),
        1, 0.0, np.ones((sub,), np.float64),
    )
    cpu_pass = (time.perf_counter() - t0) * (rows / sub)

    def _resilience_extras(summary):
        """Fault accounting for a long streamed run (utils/resilience
        .py): at north-star scale a pass takes minutes, so retries and
        degradations that silently stretched the wall must be visible in
        the metric they stretched."""
        res = (
            summary.get("resilience") if isinstance(summary, dict)
            else getattr(summary, "resilience", None)
        )
        if not res or not res.get("faults"):
            return {}
        return {
            "fault_retries": res["retries"],
            "fault_degradations": res["degradations"],
            "fault_backoff_sec": round(res["backoff_s"], 3),
        }

    def _checkpoint_extras(summary):
        """Checkpoint write overhead for a streamed run (ROADMAP item 4
        follow-on): when elastic-worlds checkpointing is armed, report
        the per-interval insurance premium — bytes and seconds per
        checkpoint interval — next to the per-pass numbers it taxes."""
        ck = (
            summary.get("checkpoint") if isinstance(summary, dict)
            else getattr(summary, "checkpoint", None)
        )
        if not ck or not ck.get("writes"):
            return {}
        return {
            "ckpt_writes": ck["writes"],
            "ckpt_bytes_per_interval": round(
                ck["bytes_written"] / ck["writes"]),
            "ckpt_sec_per_interval": round(
                ck["write_seconds"] / ck["writes"], 4),
        }

    def _overlap_extras(timings, phase):
        """Prefetch-pipeline report for a streamed phase: the
        stage/transfer/compute split (data/prefetch.py) and the fraction
        of staging hidden behind compute.  The split proves WHERE a
        streamed pass spends its wall — a tunnel-bound environment shows
        transfer ~= compute with high overlap; a compute-bound one shows
        staging fully hidden."""
        eff = timings.overlap_efficiency(phase)
        if eff is None:
            return {}
        sub = timings.subphases(phase)
        return {
            "overlap_efficiency": round(eff, 3),
            "stage_sec": round(sub.get("stage", 0.0), 3),
            "transfer_sec": round(sub.get("transfer", 0.0), 3),
            "compute_sec": round(sub.get("compute", 0.0), 3),
        }

    src = ChunkSource(gen, d, chunk_rows=chunk_rows, n_rows=rows)
    t0 = time.perf_counter()
    m = KMeans(k=k, seed=1, init_mode="random", max_iter=max_iter).fit(src)
    t_fit = time.perf_counter() - t0
    assert getattr(m.summary, "streamed", False)
    ph = m.summary.timings.as_dict()
    n_iter = max(int(m.summary.num_iter), 1)
    per_pass = ph["lloyd_loop"] / n_iter
    bytes_per_pass = rows * d * 4
    _emit(
        f"streamed_kmeans_{rows}x{d}_k{k}_sec_per_pass",
        per_pass, "sec/pass", cpu_pass / per_pass,
        rows_per_sec=round(rows / per_pass),
        effective_MBps=round(bytes_per_pass / per_pass / 1e6),
        n_iter=n_iter, init_sec=round(ph.get("init_centers", 0.0), 1),
        fit_sec=round(t_fit, 1),
        **_overlap_extras(m.summary.timings, "lloyd_loop"),
        **_compile_extras(m.summary.timings, "lloyd_loop",
                          getattr(m.summary, "progcache", None)),
        **_resilience_extras(m.summary),
        **_checkpoint_extras(m.summary),
    )
    # span-tree view of the same fit (telemetry/export.report): per-phase
    # walls, overlap, compile split — the human cross-check of the JSON
    from oap_mllib_tpu import telemetry

    print(telemetry.report(m.summary), flush=True)

    t0 = time.perf_counter()
    p = PCA(k=16).fit(src)
    t_fit_p = time.perf_counter() - t0
    assert p.summary["streamed"] and p.summary["n_rows"] == rows
    php = p.summary["timings"].as_dict()
    per_pass_p = php["covariance_streamed"] / 2  # two-pass centered Gram
    _emit(
        f"streamed_pca_{rows}x{d}_sec_per_pass",
        per_pass_p, "sec/pass", 1.0,
        effective_MBps=round(bytes_per_pass / per_pass_p / 1e6),
        eigh_sec=round(php.get("eigh", 0.0), 3),
        fit_sec=round(t_fit_p, 1),
        **_overlap_extras(p.summary["timings"], "covariance_streamed"),
        **_compile_extras(p.summary["timings"], "covariance_streamed",
                          p.summary.get("progcache")),
        **_resilience_extras(p.summary),
        **_checkpoint_extras(p.summary),
    )
    print(telemetry.report(p.summary), flush=True)


# ---------------------------------------------------------------------------
# Heterogeneous-fleet skew sweep (bench.py --skew, ISSUE 15)
# ---------------------------------------------------------------------------


def bench_skew(rows: int = 1 << 18, d: int = 64, k: int = 64,
               slow_factor: float = 4.0, emit: bool = True) -> dict:
    """Equal vs capability-weighted layout on a synthetically slowed
    rank (parallel/balance.py): a 2-rank world is SIMULATED in one
    process — each rank's Lloyd assignment pass walks its planned
    extent through the real per-chunk program, rank 1 paying a
    per-chunk sleep calibrated to ``slow_factor`` x the measured chunk
    time (a throttled host / CPU rank stand-in); the world's pass wall
    is the slowest rank's (the pass barrier).  Emits the
    ``hetero_speedup`` headline (equal wall / weighted wall — > 1 means
    the capability plan pays) plus both walls and the cross-layout
    parity, every line backend-tagged for dev/bench_regress.py's
    per-(metric, backend) gating."""
    from oap_mllib_tpu.data.stream import ChunkSource
    from oap_mllib_tpu.ops import stream_ops
    from oap_mllib_tpu.parallel import balance

    chunk = 1 << 13
    world = 2
    rng = np.random.default_rng(0)
    x = rng.normal(size=(rows, d)).astype(np.float32)
    centers = np.ascontiguousarray(x[:k], np.float32)

    def _src(lo, n_loc, sleep_s):
        def gen():
            for s in range(lo, lo + n_loc, chunk):
                if sleep_s > 0:
                    time.sleep(sleep_s)
                yield x[s: s + min(chunk, lo + n_loc - s)]

        return ChunkSource(gen, d, chunk, n_rows=n_loc)

    def _pass(lo, n_loc, sleep_s):
        t0 = time.perf_counter()
        sums, counts, _ = stream_ops.streamed_accumulate(
            _src(lo, n_loc, sleep_s), centers, np.float32,
            "highest", need_cost=False,
        )
        return time.perf_counter() - t0, sums, counts

    # calibrate: one warm pass over an equal shard measures the real
    # per-chunk time; the slow rank then sleeps (slow_factor - 1) x that
    # per chunk — its effective throughput is 1/slow_factor
    half = (rows // 2 // chunk) * chunk
    _pass(0, half, 0.0)  # warm (compile)
    base_wall, _, _ = _pass(0, half, 0.0)
    per_chunk = base_wall / max(1, half // chunk)
    sleep_s = per_chunk * (slow_factor - 1.0)

    weights = {
        "equal": [1.0, 1.0],
        "weighted": [1.0, 1.0 / slow_factor],
    }
    walls = {}
    centers_out = {}
    for layout, w in weights.items():
        extents, _ = balance.plan_extents(rows, chunk, w)
        rank_walls = []
        agg_s = np.zeros((k, d), np.float32)
        agg_c = np.zeros((k,), np.float32)
        for r, (lo, n_loc) in enumerate(extents):
            if n_loc == 0:
                rank_walls.append(0.0)
                continue
            wall, sums, counts = _pass(
                lo, n_loc, sleep_s if r == 1 else 0.0
            )
            rank_walls.append(wall)
            agg_s += np.asarray(sums)
            agg_c += np.asarray(counts)
        walls[layout] = max(rank_walls)
        centers_out[layout] = agg_s / np.maximum(agg_c[:, None], 1e-30)
    speedup = walls["equal"] / max(walls["weighted"], 1e-9)
    parity = float(np.max(np.abs(
        centers_out["equal"] - centers_out["weighted"]
    )))
    out = {
        "hetero_speedup": round(speedup, 4),
        "equal_wall_s": round(walls["equal"], 4),
        "weighted_wall_s": round(walls["weighted"], 4),
        "parity": parity,
        "slow_factor": slow_factor,
    }
    if emit:
        _emit(
            "hetero_speedup", speedup, "x", 1.0,
            equal_wall_s=out["equal_wall_s"],
            weighted_wall_s=out["weighted_wall_s"],
            parity=round(parity, 8), slow_factor=slow_factor,
            rows=rows, d=d, world=world,
        )
        _emit("hetero_equal_wall", walls["equal"], "sec", 1.0,
              slow_factor=slow_factor, rows=rows, d=d)
        _emit("hetero_weighted_wall", walls["weighted"], "sec", 1.0,
              slow_factor=slow_factor, rows=rows, d=d)
    return out


# ---------------------------------------------------------------------------
# Compile-amortization size sweep (bench.py --compile-sweep)
# ---------------------------------------------------------------------------


def bench_compile_sweep(n_sizes: int = 10, d: int = 16, k: int = 8,
                        max_iter: int = 3, emit: bool = True) -> dict:
    """Fits at ``n_sizes`` distinct row counts (same d/k), shape
    bucketing off then on, counting REAL XLA backend compiles per fit
    (progcache.xla_compile_count — the monitoring-event ground truth,
    not the registry's opinion) and cross-checking per-fit parity
    between the two modes.

    Sizes are chosen so every fit has a DISTINCT exact-padded shape
    (one new compile set per fit with bucketing off — today's behavior)
    while all land in ONE geometric bucket (zero new compiles after the
    first fit with bucketing on).  The per-mode warm-up (first size) is
    reported separately from the steady tail, which is what the CI gate
    asserts on (dev/compile_gate.py).  Returns the result dict; with
    ``emit`` prints the usual one-line JSON.
    """
    from oap_mllib_tpu.config import get_config, set_config
    from oap_mllib_tpu.models.kmeans import KMeans
    from oap_mllib_tpu.parallel.mesh import get_mesh
    from oap_mllib_tpu.utils import progcache

    mesh = get_mesh()
    m0 = mesh.shape[mesh.axis_names[0]] * 256  # the table's pad multiple
    # sizes (16*m0, 32*m0]: exact pads (17..16+n)*m0 are all distinct,
    # the x2 bucket 32*m0 is shared — and is NOT any size's exact pad,
    # so the off sweep can never pre-compile the on sweep's program
    if n_sizes > 15:
        raise ValueError("n_sizes must be <= 15 (one x2 bucket spans 16)")
    sizes = [(16 + j) * m0 - 13 for j in range(1, n_sizes + 1)]
    rng = np.random.default_rng(11)
    x = rng.normal(size=(sizes[-1], d)).astype(np.float32) * 2.0

    prior = get_config().shape_bucketing
    out = {"sizes": sizes, "d": d, "k": k}
    centers = {}
    try:
        for mode in ("off", "on"):  # off FIRST (see sizes note above)
            set_config(shape_bucketing=mode)
            cache0 = progcache.stats()
            per_fit = []
            secs0 = progcache.xla_compile_secs()
            t0 = time.perf_counter()
            cents = []
            for n in sizes:
                c0 = progcache.xla_compile_count()
                model = KMeans(
                    k=k, seed=5, init_mode="random", max_iter=max_iter
                ).fit(x[:n])
                per_fit.append(progcache.xla_compile_count() - c0)
                cents.append(model.cluster_centers_)
            out[f"wall_sec_{mode}"] = round(time.perf_counter() - t0, 2)
            out[f"xla_compile_sec_{mode}"] = round(
                progcache.xla_compile_secs() - secs0, 2
            )
            out[f"compiles_{mode}"] = sum(per_fit)
            out[f"warm_compiles_{mode}"] = per_fit[0]
            out[f"steady_compiles_{mode}"] = sum(per_fit[1:])
            delta = progcache.delta(cache0)
            if delta.get("hit_rate") is not None:
                out[f"hit_rate_{mode}"] = round(delta["hit_rate"], 3)
            centers[mode] = cents
    finally:
        set_config(shape_bucketing=prior)

    # parity: same data, same seed — bucketing must not change the fit
    # (padding rows are weight-0; only summation order differs)
    out["parity_max_dev"] = float(
        max(
            np.abs(a - b).max()
            for a, b in zip(centers["off"], centers["on"])
        )
    )
    ratio = out["steady_compiles_off"] / max(out["steady_compiles_on"], 1)
    out["steady_compile_ratio"] = round(ratio, 2)

    # tuned leg: a pinned non-default walk geometry must ride the SAME
    # compile-amortization planes — the bucketed program cache within
    # the process (second same-bucket fit adds ZERO XLA compiles) and
    # the persistent XLA cache across processes (its executables land
    # on disk, so a warm restart skips backend compilation for tuned
    # programs exactly as it does for default-geometry ones)
    import shutil
    import tempfile

    prior_tuning = get_config().tuning
    xdir = tempfile.mkdtemp(prefix="oap-bench-xla-cache-")
    try:
        set_config(
            shape_bucketing="on",
            tuning='pin:{"kmeans": {"tile_rows": 256, "depth": 3}}',
            compilation_cache_dir=xdir,
        )
        c0 = progcache.xla_compile_count()
        KMeans(k=k, seed=5, init_mode="random", max_iter=max_iter).fit(
            x[: sizes[0]]
        )
        out["tuned_warm_compiles"] = progcache.xla_compile_count() - c0
        c1 = progcache.xla_compile_count()
        KMeans(k=k, seed=5, init_mode="random", max_iter=max_iter).fit(
            x[: sizes[1]]  # distinct exact shape, same x2 bucket
        )
        out["tuned_steady_compiles"] = progcache.xla_compile_count() - c1
        out["tuned_cache_entries"] = sum(
            len(fs) for _, _, fs in os.walk(xdir)
        )
        assert out["tuned_steady_compiles"] == 0, (
            "pinned tuned geometry broke bucketed program reuse: "
            f"{out['tuned_steady_compiles']} new XLA compiles on the "
            "second same-bucket fit"
        )
        assert out["tuned_cache_entries"] > 0, (
            "tuned programs did not land in the persistent XLA "
            f"compilation cache at {xdir}"
        )
    finally:
        set_config(shape_bucketing=prior, tuning=prior_tuning,
                   compilation_cache_dir="")
        # un-wire jax's persistent cache before deleting its dir, so
        # later bench legs neither write into a dead path nor report
        # cache-hit-deflated compile counts
        try:
            import jax

            from jax._src import compilation_cache as _cc

            jax.config.update("jax_compilation_cache_dir", None)
            _cc.reset_cache()
            progcache._persist_applied = None
        except Exception:
            pass
        shutil.rmtree(xdir, ignore_errors=True)

    if emit:
        _emit(
            "kmeans_compile_sweep_10sizes", ratio, "x fewer XLA compiles",
            ratio, **{k2: v for k2, v in out.items() if k2 != "sizes"},
        )
    return out


# ---------------------------------------------------------------------------
# Mixed-precision policy sweep (bench.py --precision-sweep)
# ---------------------------------------------------------------------------


def bench_precision_sweep(emit: bool = True) -> dict:
    """Fit all three estimators under each compute-precision policy
    (utils/precision.py) on fixed seeds, reporting throughput
    (iters/sec for K-Means, fits/sec for PCA, iters/sec for ALS) AND
    parity vs the f32 policy — the same metrics dev/precision_gate.py
    asserts, recorded instead of gated, so a BASELINE row can show what
    each policy buys and costs on this backend.  CI-affordable shapes;
    on a real TPU the bf16 rows are the MFU-movers (half the operand
    HBM bytes, 2x MXU throughput)."""
    from oap_mllib_tpu.config import get_config, set_config
    from oap_mllib_tpu.models.als import ALS
    from oap_mllib_tpu.models.kmeans import KMeans
    from oap_mllib_tpu.models.pca import PCA
    from oap_mllib_tpu.utils.precision import TIERS

    rng = np.random.default_rng(17)
    n, d, k = 1 << 15, 64, 32
    proto = rng.normal(size=(k, d)).astype(np.float32) * 4.0
    x = (proto[rng.integers(k, size=n)]
         + rng.normal(size=(n, d)).astype(np.float32) * 0.3)
    nu, ni, nnz, rank = 1500, 900, 60_000, 8
    users = rng.integers(nu, size=nnz).astype(np.int64)
    items = rng.integers(ni, size=nnz).astype(np.int64)
    ratings = (rng.random(nnz) * 4 + 1).astype(np.float32)
    km_iters, als_iters = 10, 5
    scale = float(np.abs(x).max())

    prior = get_config().compute_precision
    out = {}
    ref = {}
    try:
        for pol in TIERS:  # f32 first: the parity reference
            set_config(compute_precision=pol)
            km = KMeans(k=k, seed=5, init_mode="random", max_iter=km_iters)
            t_km = _best_of(lambda: km.fit(x), reps=2)
            m = km.fit(x)
            t_pca = _best_of(lambda: PCA(k=8).fit(x), reps=2)
            p = PCA(k=8).fit(x)
            als = ALS(rank=rank, max_iter=als_iters, seed=3,
                      implicit_prefs=True, alpha=10.0)
            t_als = _best_of(lambda: als.fit(users, items, ratings), reps=2)
            a = als.fit(users, items, ratings)
            pred = a.predict(users[:2000], items[:2000])
            row = {
                "kmeans_iters_per_sec": round(
                    max(int(m.summary.num_iter), 1) / t_km, 3
                ),
                "pca_fits_per_sec": round(1.0 / t_pca, 3),
                "als_iters_per_sec": round(als_iters / t_als, 3),
                "policy_recorded": m.summary.precision,
            }
            if pol == "f32":
                ref = {
                    "centers": np.sort(m.cluster_centers_, axis=0),
                    "cost": m.summary.training_cost,
                    "pc": p.components_,
                    "pred": pred,
                }
            else:
                row["kmeans_centroid_rel_dev"] = float(
                    np.abs(
                        np.sort(m.cluster_centers_, axis=0) - ref["centers"]
                    ).max() / scale
                )
                row["kmeans_cost_rel_dev"] = float(
                    abs(m.summary.training_cost - ref["cost"])
                    / max(ref["cost"], 1e-30)
                )
                # principal-subspace angle via the singular values of
                # the cross-projection (order/sign-free)
                s = np.linalg.svd(ref["pc"].T @ p.components_,
                                  compute_uv=False)
                row["pca_subspace_rad"] = float(
                    np.arccos(np.clip(s.min(), 0.0, 1.0))
                )
                row["als_pred_rel_rmse"] = float(
                    np.sqrt(np.mean((pred - ref["pred"]) ** 2))
                    / max(float(np.sqrt(np.mean(ref["pred"] ** 2))), 1e-30)
                )
            out[pol] = row
            if emit:
                _emit(
                    "precision_sweep", row["kmeans_iters_per_sec"],
                    "kmeans iters/sec", 1.0, precision=pol,
                    **{k2: v for k2, v in row.items()
                       if k2 != "kmeans_iters_per_sec"},
                )
    finally:
        set_config(compute_precision=prior)
    return out


def _tests_tpu_status(timeout=900):
    """Run the compiled-mode TPU suite and report its outcome, so the
    bench artifact itself proves whether compiled-Pallas coverage ran on
    this backend (VERDICT r2 item 9)."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", "tests_tpu/", "-q", "--no-header"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return "timeout"
    tail = (proc.stdout.strip().splitlines() or [""])[-1]
    if proc.returncode == 0:
        return tail  # e.g. "6 passed in 104s" or "6 skipped ..."
    return f"FAILED: {tail}"


def bench_serving(requests: int = 200, sweep_users: int = 1_000_000,
                  emit: bool = True) -> dict:
    """Serving-plane bench (ISSUE 13): the BENCH JSON's second headline
    next to iters/sec.

    Leg 1 — request storm: a served K-Means model answers ``requests``
    jittered-size batches after a bucket-family warmup; reports
    sustained QPS, p50/p99 tail latency (per-request walls, host
    round-trip included), rows/sec, and the steady-state XLA compile
    count (MUST be zero — ground truth via xla_compile_count).

    Leg 2 — full-sweep top-k: ``recommend_for_all_users`` over a
    ``sweep_users``-row synthetic factor table through the streamed,
    prefetch-pipelined sweep (serving/sweep.py) — users/sec with the
    quadratic score matrix never materialized.

    Leg 3 — multi-process fleet storm (ISSUE 16): a REAL 2-replica
    world (tests/pseudo_cluster_worker_traffic.py, bench mode) drives
    sustained jittered storms through each replica's async
    TrafficQueue; the ``serving_kmeans_qps_mp`` headline is the
    fleet-aggregate QPS.  Hosts that cannot spawn a multiprocess jax
    world WARN and skip the leg (bench_regress is name-keyed and
    warn-skips absent metrics)."""
    import numpy as np

    from oap_mllib_tpu import serving
    from oap_mllib_tpu.models.als import ALSModel
    from oap_mllib_tpu.models.kmeans import KMeans
    from oap_mllib_tpu.serving import sweep as sweep_mod
    from oap_mllib_tpu.utils import progcache

    rng = np.random.default_rng(7)
    d, k, max_rows = 64, 64, 2048
    x = rng.normal(size=(max_rows * 2, d)).astype(np.float32)
    model = KMeans(k=k, seed=0, init_mode="random", max_iter=3).fit(x)
    handle = serving.serve(model)
    handle.warmup(max_rows)
    sizes = rng.integers(1, max_rows, size=requests)
    before = progcache.xla_compile_count()
    walls = []
    t0 = time.perf_counter()
    for s in sizes:
        t1 = time.perf_counter()
        handle.predict(x[: int(s)])
        walls.append(time.perf_counter() - t1)
    storm_wall = time.perf_counter() - t0
    steady_compiles = progcache.xla_compile_count() - before
    walls.sort()
    p50 = walls[len(walls) // 2]
    p99 = walls[min(len(walls) - 1, int(len(walls) * 0.99))]
    qps = requests / storm_wall
    rows = int(np.sum(sizes))
    block = serving.serving_summary()
    attribution = _bench_serving_attribution(handle, x, sizes)
    if emit:
        _emit(
            "serving_kmeans_qps", qps, "req/sec", 0.0,
            p50_ms=round(p50 * 1e3, 3), p99_ms=round(p99 * 1e3, 3),
            rows_per_sec=round(rows / storm_wall, 1),
            steady_compiles=steady_compiles,
            pad_rows=block["pad_rows"], requests=requests,
            batch_d=d, batch_k=k, **attribution,
        )

    nu, ni, r, topk = int(sweep_users), 256, 16, 10
    uf = rng.normal(size=(nu, r)).astype(np.float32)
    itf = rng.normal(size=(ni, r)).astype(np.float32)
    als = ALSModel(uf, itf)
    t0 = time.perf_counter()
    ids = sweep_mod.recommend_for_all_users(als, topk)
    sweep_wall = time.perf_counter() - t0
    assert ids.shape == (nu, topk)
    users_per_sec = nu / sweep_wall
    if emit:
        _emit(
            "serving_als_sweep_users_per_sec", users_per_sec,
            "users/sec", 0.0,
            sweep_users=nu, n_items=ni, rank=r, top_k=topk,
            sweep_wall_sec=round(sweep_wall, 2),
        )
    # the brownout + fleet legs only price into emitting runs —
    # in-process callers (dev/serve_gate.py leg 5) measure the
    # single-process storm only
    bo = _bench_serving_brownout(handle, x, sizes, emit) if emit else None
    mp = bench_serving_mp(emit=True) if emit else None
    return {
        "qps": qps, "p50_s": p50, "p99_s": p99,
        "steady_compiles": steady_compiles,
        "users_per_sec": users_per_sec,
        "qps_brownout": None if bo is None else bo["qps"],
        "qps_mp": None if mp is None else mp["qps_mp"],
    }


def _bench_serving_attribution(handle, x, sizes) -> dict:
    """Deadline-budget attribution fields for the ``--serving`` line
    (ISSUE 19): a short traced storm through the async TrafficQueue
    (``serve_trace_sample=1.0``) whose per-stage p99s say where a
    request's wall goes — fields are name-keyed extras, so
    dev/bench_regress.py picks them up with no changes."""
    from oap_mllib_tpu.config import get_config, set_config
    from oap_mllib_tpu.serving import reqtrace, traffic as traffic_mod

    prev = float(get_config().serve_trace_sample)
    n = min(100, len(sizes))
    set_config(serve_trace_sample=1.0)
    try:
        with traffic_mod.TrafficQueue(handle) as q:
            futs = [
                q.submit(x[: int(s)], deadline_ms=0.0)
                for s in sizes[:n]
            ]
            for f in futs:
                f.result(timeout=60)
        sq = reqtrace.stage_quantiles()
    finally:
        set_config(serve_trace_sample=prev)

    def p99_ms(stage: str) -> float:
        return round(sq.get(stage, {}).get("p99_s", 0.0) * 1e3, 3)

    return {
        "queue_wait_p99_ms": p99_ms("queue_wait"),
        "batch_form_p99_ms": p99_ms("batch_form"),
        "execute_p99_ms": p99_ms("execute"),
    }


def _bench_serving_brownout(handle, x, sizes, emit: bool) -> dict:
    """Degraded-mode headline (ISSUE 18): the same jittered storm
    through the async TrafficQueue with the brownout ladder pinned at
    its top rung (reduced top-k + bf16 + stale pins all active), two
    transient dispatcher faults armed (the retry envelope), and two
    NaN-payload requests (poison bisection) — ``serving_kmeans_qps_
    brownout`` is the throughput a browned-out replica still sustains,
    with the retry/poison counters it booked along the way."""
    import numpy as np

    from oap_mllib_tpu import serving
    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.serving import traffic as traffic_mod
    from oap_mllib_tpu.telemetry import metrics as tm

    requests = len(sizes)
    retries0 = int(tm.family_total("oap_serve_retries_total"))
    poison0 = int(tm.family_total("oap_serve_poison_total"))
    try:
        set_config(serve_brownout="pin:stale",
                   fault_spec="serve.dispatch:fail=2")
        traffic_mod._reset_for_tests()
        # the degraded precision policy (bf16 rung) compiles its own
        # bucket family — warm it so the storm stays compile-free
        handle.warmup(2048)
        nan_at = {3, requests // 2}
        reqs = []
        for i, s in enumerate(sizes):
            b = x[: int(s)]
            if i in nan_at:
                b = b.copy()
                b[0, 0] = np.nan
            reqs.append(b)
        walls = []
        t0 = time.perf_counter()
        with serving.TrafficQueue(handle) as q:
            futs = [
                (time.perf_counter(), q.submit(b, deadline_ms=120_000))
                for b in reqs
            ]
            for ts, f in futs:
                try:
                    f.result(timeout=120)
                except serving.ServeError:
                    pass  # the quarantined poison payloads
                walls.append(time.perf_counter() - ts)
        storm_wall = time.perf_counter() - t0
    finally:
        set_config(serve_brownout="auto", fault_spec="")
        traffic_mod._reset_for_tests()
    walls.sort()
    p50 = walls[len(walls) // 2]
    p99 = walls[min(len(walls) - 1, int(len(walls) * 0.99))]
    qps = requests / storm_wall
    retried = int(tm.family_total("oap_serve_retries_total")) - retries0
    poison = int(tm.family_total("oap_serve_poison_total")) - poison0
    if emit:
        _emit(
            "serving_kmeans_qps_brownout", qps, "req/sec", 0.0,
            p50_ms=round(p50 * 1e3, 3), p99_ms=round(p99 * 1e3, 3),
            rung="stale", requests=requests,
            retried=retried, poison=poison,
        )
    return {"qps": qps, "retried": retried, "poison": poison}


# environment-incapability signatures (mirrors tests/test_pseudo_cluster
# .py): a worker that died on one of these means this HOST cannot form
# a multiprocess jax world — warn + skip, not a bench failure
_MP_ENV_FAILURE_MARKERS = (
    "Multiprocess computations aren't implemented",
    "UNIMPLEMENTED",
    "Unable to initialize backend",
    "failed to join world",
    "DEADLINE_EXCEEDED",
    "Failed to connect to coordinator",
)


def bench_online(new_users: int = 10_000, emit: bool = True) -> dict:
    """Online-learning bench (ISSUE 20): the delta-commit headline.

    Folds ``new_users`` brand-new users (6 ratings each) into a fitted
    ALS model through the batched fold-in solve (online/foldin.py) and
    prices it against the nightly-refit alternative: a full
    from-scratch fit on base + delta at the same max_iter.  A small
    warming delta compiles the bucketed solve first, so the timed
    commit is the steady state a live service pays per delta.

    Emits ``als_foldin_users_per_sec`` and ``online_speedup_vs_refit``
    (refit wall / fold-in wall; the acceptance bound at this scale is
    >= 20x).  The prediction-space parity of the folded rows vs the
    refit (rel Frobenius over the grown rows' score vectors — factor
    rows are only unique up to an invertible transform, so
    prediction space is the meaningful comparison; documented bound
    0.15, docs/user-guide.md) rides both lines."""
    from oap_mllib_tpu.models.als import ALS

    rng = np.random.default_rng(15)
    nu, ni, rank, nnz = 20_000, 500, 8, 300_000
    u = rng.integers(0, nu, size=nnz)
    i = rng.integers(0, ni, size=nnz)
    r = rng.normal(1.0, 0.5, size=nnz).astype(np.float32)
    est = dict(rank=rank, max_iter=5, reg_param=0.1, seed=6,
               num_user_blocks=1)
    base = ALS(**est).fit(u, i, r, n_users=nu, n_items=ni)

    def _delta(lo, n):
        du = np.repeat(np.arange(lo, lo + n), 6)
        di = rng.integers(0, ni, size=du.size).astype(np.int64)
        dr = rng.normal(1.0, 0.5, size=du.size).astype(np.float32)
        return du, di, dr

    # warming delta in the SAME power-of-two shape buckets as the
    # timed one (edges and destination rows both land one bucket)
    warm_n = max(1, int(new_users * 0.9))
    du1, di1, dr1 = _delta(nu, warm_n)
    du2, di2, dr2 = _delta(nu + warm_n, new_users)
    base.fold_in_users(du1, di1, dr1)
    t0 = time.perf_counter()
    base.fold_in_users(du2, di2, dr2)
    foldin_wall = time.perf_counter() - t0

    t0 = time.perf_counter()
    refit = ALS(**est).fit(
        np.concatenate([u, du1, du2]), np.concatenate([i, di1, di2]),
        np.concatenate([r, dr1, dr2]),
        n_users=nu + warm_n + new_users, n_items=ni,
    )
    refit_wall = time.perf_counter() - t0

    pred_fold = base.user_factors_[nu:] @ base.item_factors_.T
    pred_refit = refit.user_factors_[nu:] @ refit.item_factors_.T
    parity = float(np.linalg.norm(pred_fold - pred_refit)
                   / np.linalg.norm(pred_refit))
    users_per_sec = new_users / foldin_wall
    speedup = refit_wall / max(foldin_wall, 1e-9)
    extra = dict(
        new_users=new_users, rank=rank, n_items=ni,
        foldin_wall_sec=round(foldin_wall, 4),
        refit_wall_sec=round(refit_wall, 2),
        parity_rel_frobenius=round(parity, 4),
    )
    if emit:
        # vs_baseline IS the refit: the delta path's win over the
        # nightly full-refit pattern it replaces (docs/migration.md)
        _emit("als_foldin_users_per_sec", users_per_sec, "users/sec",
              speedup, **extra)
        _emit("online_speedup_vs_refit", speedup, "x", speedup, **extra)
    return {
        "users_per_sec": users_per_sec, "speedup": speedup,
        "parity": parity, "foldin_wall": foldin_wall,
        "refit_wall": refit_wall,
    }


def bench_serving_mp(nproc: int = 2, requests: int = 200,
                     emit: bool = True):
    """Fleet-QPS headline: spawn ``nproc`` bench-mode traffic workers
    as a real multi-process world, parse each replica's ``BENCH_QPS``
    line, and emit the aggregate as ``serving_kmeans_qps_mp``.
    Returns None (after a WARN) when this host cannot spawn the world
    — the regression harness warn-skips metrics absent from a run."""
    import subprocess
    import tempfile

    from oap_mllib_tpu.parallel.bootstrap import free_port

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "tests", "pseudo_cluster_worker_traffic.py")
    env = dict(os.environ)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["TRAFFIC_WORKER_MODE"] = "bench"
    env["TRAFFIC_BENCH_REQUESTS"] = str(requests)
    with tempfile.TemporaryDirectory() as crash_dir:
        env["TRAFFIC_CRASH_DIR"] = crash_dir
        coord = f"127.0.0.1:{free_port('127.0.0.1', 4000)}"
        procs = [
            subprocess.Popen(
                [sys.executable, worker, str(r), str(nproc), coord, "1"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env, cwd=repo,
            )
            for r in range(nproc)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=300)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
    per_rank = []
    for p, out in zip(procs, outs):
        if any(m in out for m in _MP_ENV_FAILURE_MARKERS):
            print("WARN: serving_kmeans_qps_mp skipped — this host "
                  "cannot form a multiprocess jax world",
                  file=sys.stderr)
            return None
        if p.returncode != 0:
            print("WARN: serving_kmeans_qps_mp skipped — bench worker "
                  f"exited {p.returncode}:\n{out[-1500:]}",
                  file=sys.stderr)
            return None
        line = [ln for ln in out.splitlines()
                if ln.startswith("BENCH_QPS ")]
        if not line:
            print("WARN: serving_kmeans_qps_mp skipped — no BENCH_QPS "
                  f"line:\n{out[-1500:]}", file=sys.stderr)
            return None
        per_rank.append(
            dict(kv.split("=", 1) for kv in line[-1].split()[1:])
        )
    # every replica stormed concurrently: the fleet answers the SUM of
    # the per-replica rates; the tail is the worst replica's tail
    qps_mp = sum(float(r["qps"]) for r in per_rank)
    p50_ms = max(float(r["p50_ms"]) for r in per_rank)
    p99_ms = max(float(r["p99_ms"]) for r in per_rank)
    if emit:
        _emit(
            "serving_kmeans_qps_mp", qps_mp, "req/sec", 0.0,
            nproc=nproc, requests_per_replica=requests,
            per_replica_qps=[round(float(r["qps"]), 1) for r in per_rank],
            p50_ms=round(p50_ms, 3), p99_ms=round(p99_ms, 3),
        )
    return {"qps_mp": qps_mp, "p50_ms": p50_ms, "p99_ms": p99_ms}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--all", action="store_true",
                    help="emit every BASELINE.md metric (one JSON line each)")
    ap.add_argument("--skip-tests-tpu", action="store_true",
                    help="omit the compiled-suite status probe (slow)")
    ap.add_argument("--mesh", type=int, default=0, metavar="N",
                    help="weak-scaling harness over 1..N ranks "
                         "(virtual CPU devices unless --mesh-backend real)")
    ap.add_argument("--mesh-backend", choices=("cpu", "real"), default="cpu",
                    help="cpu: force an N-device virtual CPU mesh (protocol "
                         "check, not ICI scaling); real: use the live "
                         "backend's devices (a TPU slice)")
    ap.add_argument("--mesh-sizes", choices=("small", "big"), default="small",
                    help="per-rank work: small = CI-affordable, big = "
                         "slice-scale shapes")
    ap.add_argument("--streamed", type=int, default=0, metavar="ROWS",
                    help="north-star streamed scale: generator-backed "
                         "K-Means + PCA at ROWS x 256 (100000000 = the "
                         "full BASELINE.json config on a pod host)")
    ap.add_argument("--compile-sweep", action="store_true",
                    help="compile-amortization sweep: K-Means fits at 10 "
                         "distinct row counts, shape bucketing off vs on, "
                         "counting real XLA compiles + checking parity")
    ap.add_argument("--precision-sweep", action="store_true",
                    help="mixed-precision policy sweep: the three "
                         "estimators under f32/tf32/bf16, reporting "
                         "throughput + parity vs f32 per policy")
    ap.add_argument("--skew", action="store_true",
                    help="heterogeneous-fleet sweep: equal vs "
                         "capability-weighted layout on a synthetically "
                         "slowed rank (simulated 2-rank world), emitting "
                         "the hetero_speedup headline + parity")
    ap.add_argument("--skew-factor", type=float, default=4.0,
                    metavar="X",
                    help="how many times slower the synthetic straggler "
                         "runs (default 4.0)")
    ap.add_argument("--online", action="store_true",
                    help="online-learning plane: ALS fold-in of 10k new "
                         "users vs a full refit on the same container "
                         "(als_foldin_users_per_sec + "
                         "online_speedup_vs_refit, prediction-space "
                         "parity riding the lines)")
    ap.add_argument("--serving", action="store_true",
                    help="serving plane: sustained QPS + p50/p99 tail "
                         "latency on a jittered request storm (zero "
                         "steady-state compiles) and full-sweep top-k "
                         "users/sec on a 1M-user synthetic factor table")
    args = ap.parse_args()

    if args.serving and "locks" in _sanitizers_state():
        # same policy as the sweep refusals below: the locks sanitizer
        # adds per-acquisition bookkeeping on the serving registry and
        # telemetry seams, so a QPS/tail-latency headline under it is
        # not comparable to the locks-off baselines
        ap.error(
            f"--serving refuses to run with the locks sanitizer armed "
            f"(Config.sanitizers={_sanitizers_state()!r}): tracked-lock "
            "bookkeeping inflates request tail latency, so the QPS/p99 "
            "headline would not be comparable to locks-off baselines; "
            "unset OAP_MLLIB_TPU_SANITIZERS for benching"
        )

    if (args.precision_sweep or args.compile_sweep) \
            and _sanitizers_state() != "off":
        # the sweeps are compile-count/throughput COMPARISONS — within
        # the run (bucketing off vs on, f32 vs bf16) and against the
        # BENCH_r* baselines, all recorded sanitizers-off.  The
        # collective sanitizer adds a gather per host collective and the
        # retrace guard perturbs compile accounting, so a sweep under a
        # different sanitizer set is not comparable: refuse instead of
        # emitting silently skewed numbers.
        ap.error(
            f"--precision-sweep/--compile-sweep refuse to run with "
            f"sanitizers armed (Config.sanitizers="
            f"{_sanitizers_state()!r}): sanitizers perturb compile "
            "counts and collective walls, so the sweep would not be "
            "comparable to sanitizers-off baselines; unset "
            "OAP_MLLIB_TPU_SANITIZERS for benching"
        )

    if args.precision_sweep:
        bench_precision_sweep()
        return

    if args.online:
        bench_online()
        return

    if args.serving:
        bench_serving()
        return

    if args.compile_sweep:
        bench_compile_sweep()
        return

    if args.skew:
        if args.skew_factor <= 1.0:
            ap.error("--skew-factor must be > 1.0")
        bench_skew(slow_factor=args.skew_factor)
        return

    if args.streamed:
        bench_streamed(args.streamed)
        return

    if args.mesh:
        if args.mesh_backend == "cpu":
            # must happen before any backend initializes (env vars alone
            # are ignored when a site hook pins the platform)
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                # older jax lines have no jax_num_cpu_devices option
                os.environ["XLA_FLAGS"] = (
                    flags
                    + f" --xla_force_host_platform_device_count={args.mesh}"
                ).strip()
            import jax

            jax.config.update("jax_platforms", "cpu")
            if hasattr(jax.config, "jax_num_cpu_devices"):
                jax.config.update("jax_num_cpu_devices", args.mesh)
        bench_mesh(args.mesh, args.mesh_backend, args.mesh_sizes)
        return

    extra = {}
    if not args.skip_tests_tpu:
        extra["tests_tpu"] = _tests_tpu_status()

    from oap_mllib_tpu.config import get_config
    from oap_mllib_tpu.utils import precision as psn

    # The compute-precision POLICY resolves first (Config
    # .compute_precision / kmeans_precision — utils/precision.py): a
    # reduced policy maps the kernel tier itself and is what the JSON's
    # `precision` field records.  Under the default f32 policy the
    # headline tier stays "high" — bf16_3x sums + bf16 assignment,
    # validated within the 1e-4 parity bar by tests_tpu (whose status
    # rides along in the same JSON line) — and an explicit env override
    # of matmul_precision still wins.
    pol = psn.resolve("kmeans")
    if pol.name != "f32":
        precision = psn.kernel_tier(pol.name, get_config().matmul_precision)
    else:
        precision = (
            get_config().matmul_precision
            if "OAP_MLLIB_TPU_MATMUL_PRECISION" in os.environ
            else "high"
        )
    if args.all:
        _, cpu_ips = bench_kmeans("high", extra=extra, policy=pol.name)
        bench_kmeans("highest", cpu_ips=cpu_ips, policy=pol.name)
        bench_pca(n=1 << 20, d=128)
        bench_pca(n=1 << 17, d=2048)  # largest-d single-chip proxy
        bench_als()
        bench_als_large()
    else:
        # the default (driver-captured) run emits ONE bound-annotated
        # headline per algorithm (VERDICT r5 item 5): K-Means MFU vs
        # bf16 peak, PCA covariance TFLOP/s + eigh wall share, ALS
        # gather indices/s vs the measured ~250M/s ceiling — so a
        # regression in ANY algorithm surfaces in BENCH_r<NN>.json.
        # (--all adds the d=2048 PCA proxy and the ML-25M ALS scale.)
        bench_kmeans(precision, extra=extra, policy=pol.name)
        bench_pca(n=1 << 20, d=128)
        bench_als()


if __name__ == "__main__":
    main()
